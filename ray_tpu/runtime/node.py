"""Node manager: process lifecycle for the multiprocess runtime.

Capability parity with the reference's node/process management
(python/ray/_private/node.py start_head_processes + services.py
start_raylet, and the raylet WorkerPool worker_pool.h:149): creates the
node's C++ shm store, serves the head, serves this node's object-plane
endpoint (chunked cross-node reads, see runtime/object_plane.py),
spawns/monitors/kills worker processes (the chaos NodeKiller hook used by
fault-tolerance tests). Secondary machines join with NodeAgent
(runtime/node_agent.py), which reuses the same worker-spawn path.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional


from ray_tpu.runtime.rpc import RpcServer

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def spawn_worker_process(head_address: str, store_name: str,
                         worker_id: str, resources: Dict[str, float],
                         node_id: str = "head",
                         force_cpu_backend: bool = False,
                         runtime_env: Optional[Dict] = None
                         ) -> subprocess.Popen:
    """Start one worker process (shared by NodeManager and NodeAgent)."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)   # breaks the TPU plugin (see skills)
    # Propagate driver-side flag overrides (chaos delays, spill
    # settings, …) to the worker, reference `_system_config` style.
    from ray_tpu._private.config import GlobalConfig
    env.update(GlobalConfig.to_env())
    if force_cpu_backend:
        env["JAX_PLATFORMS"] = "cpu"
    # The worker watches this pid and exits when it dies (see
    # worker_main._watch_parent) — even on SIGKILL of the spawner no
    # orphan keeps holding RPC ports and the shm segment.
    # (PR_SET_PDEATHSIG is unsuitable: it fires when the spawning
    # THREAD exits, and RPC handler threads spawn workers too.)
    env["RAY_TPU_PARENT_PID"] = str(os.getpid())
    cmd = [sys.executable, "-m", "ray_tpu.runtime.worker_main",
           "--head", head_address,
           "--store", store_name,
           "--worker-id", worker_id,
           "--node-id", node_id,
           "--resources", json.dumps(resources)]
    if runtime_env:
        # Dedicated env-keyed worker (worker_pool.h:149 parity): the
        # env is applied once at startup; the process IS the env.
        cmd += ["--runtime-env", json.dumps(runtime_env)]
        if runtime_env.get("container"):
            # Container env: the worker runs inside the image with
            # host networking + /dev/shm + the repo mounted through
            # (reference: runtime_env/container.py wraps the worker
            # command in podman run).
            from ray_tpu._private.runtime_env import \
                container_command_prefix
            pass_env = {k: v for k, v in env.items()
                        if k.startswith(("RAY_TPU_", "JAX_", "XLA_"))}
            prefix = container_command_prefix(runtime_env,
                                              env_vars=pass_env)
            cmd = prefix + ["python", "-m",
                            "ray_tpu.runtime.worker_main"] + cmd[3:]
    return subprocess.Popen(cmd, cwd=_REPO_ROOT, env=env)


class _NodeService:
    """Worker-process lifecycle RPC served by the node manager — the
    head (its own process, like the reference's gcs_server) calls back
    into it for request_worker/stop_worker."""

    def __init__(self, nm: "NodeManager"):
        self._nm = nm

    def start_worker(self, index: int,
                     resources: Optional[Dict[str, float]] = None,
                     runtime_env: Optional[Dict] = None) -> str:
        return self._nm.start_worker(index, resources, runtime_env)

    def kill_worker(self, worker_id: str) -> None:
        self._nm.kill_worker(worker_id)

    def num_workers(self) -> int:
        return len(self._nm.procs)


class _HeadProxy:
    """Method-call proxy so in-process consumers (tests, fixtures) can
    keep calling `node.head_service.X(...)` with the head in its own
    process."""

    def __init__(self, client):
        self._client = client

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            return self._client.call(name, *args, **kwargs)
        return call


class NodeManager:
    def __init__(self, num_workers: int = 2,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 store_capacity: int = 256 * 1024 * 1024,
                 tpu_owner_worker: Optional[int] = None):
        self.resources_per_worker = resources_per_worker or {"CPU": 2}
        # Root of the cluster's process tree: mint the shared RPC
        # secret here so every spawned process (head, workers, node
        # agents) authenticates; external drivers attach by setting
        # RAY_TPU_cluster_token.
        from ray_tpu._private.config import ensure_cluster_token
        ensure_cluster_token()
        self.store_name = f"/raytpu_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        from ray_tpu._private.shm_store import ShmObjectStore
        self.store = ShmObjectStore.create(self.store_name,
                                           store_capacity)
        # Native metrics segment: workers record with lock-free atomics,
        # the head aggregates without RPC (N20, src/metrics/).
        from ray_tpu._private.shm_metrics import ShmMetricsRegistry
        self.metrics = ShmMetricsRegistry.create(self.store_name + "_m")
        # The head is its own PROCESS (gcs_server parity): scheduler
        # loops and dispatch senders don't share the driver's GIL. Its
        # durable tables snapshot into _state_dir for restart recovery.
        self._state_dir: Optional[str] = None
        self.head_proc = self._spawn_head()
        from ray_tpu.runtime.rpc import RpcClient
        self.head_client = RpcClient(self._head_address)
        self.head_service = _HeadProxy(self.head_client)
        # Serve worker-lifecycle callbacks for the head.
        self.node_server = RpcServer(_NodeService(self))
        self.head_client.call("attach_node_service",
                              self.node_server.address)
        # This node's object-plane endpoint + membership entry. The
        # service owns the node's TRANSFER plane: workers delegate
        # bulk fetches to it (ObjectService.fetch_object).
        from ray_tpu.runtime.object_plane import (ObjectPlane,
                                                  ObjectService,
                                                  prewarm_transfer_path)
        self._service_plane = ObjectPlane(
            self.store, RpcClient(self._head_address), node_id="head",
            is_node_service=True)
        self.object_service = ObjectService(self.store,
                                            plane=self._service_plane)
        self.object_server = RpcServer(self.object_service)
        self.head_client.call("register_node", "head",
                              self.object_server.address,
                              self.store_name)
        self._service_plane.refresh_multinode()
        prewarm_transfer_path(self.store, self.object_server.address)
        # Owner-driven eager free: the head broadcasts freed ids on
        # `object_free` (including borrower-protocol frees of escaped
        # objects) — the HEAD node's copies drop here, same as every
        # agent node (node_agent.py does the same for its store).
        try:
            from ray_tpu._private.ids import ObjectID
            from ray_tpu.runtime.pubsub import Subscriber
            self._free_sub = Subscriber(RpcClient(self._head_address))

            def _on_free(_seq, item):
                for oid_hex in item.get("oids", ()):
                    try:
                        self.store.delete(ObjectID.from_hex(oid_hex))
                    except Exception:
                        pass      # not on this node: fine
            self._free_sub.subscribe_stream("object_free", _on_free)
        except Exception:
            self._free_sub = None
        self.procs: Dict[str, subprocess.Popen] = {}
        self.tpu_owner_worker = tpu_owner_worker
        self._stopped = False
        for i in range(num_workers):
            self.start_worker(i)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True, name="node-monitor")
        self._monitor.start()

    def _spawn_head(self, port: int = 0) -> subprocess.Popen:
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env["JAX_PLATFORMS"] = "cpu"     # the head never touches a TPU
        from ray_tpu._private.config import GlobalConfig
        env.update(GlobalConfig.to_env())
        if self._state_dir is None:
            import tempfile
            self._state_dir = tempfile.mkdtemp(prefix="raytpu_head_")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.runtime.head_main",
             "--store", self.store_name,
             "--port", str(port),
             "--state-dir", self._state_dir],
            cwd=_REPO_ROOT, env=env, stdout=subprocess.PIPE, text=True)
        line = proc.stdout.readline()
        if "address=" not in line:
            raise RuntimeError(f"head failed to start: {line!r}")
        self._head_address = line.split("address=")[1].strip()
        return proc

    def restart_head(self):
        """Respawn the head at the SAME address from its persisted
        snapshot (head fault tolerance: clients keep their address;
        workers re-attach via heartbeats). Also the chaos hook for
        kill-the-head tests."""
        try:
            self.head_proc.kill()
            self.head_proc.wait(timeout=10)
        except Exception:
            pass
        port = int(self._head_address.rsplit(":", 1)[1])
        # The old socket may linger in TIME_WAIT; retry binding briefly.
        deadline = time.time() + 15
        while True:
            try:
                self.head_proc = self._spawn_head(port=port)
                break
            except RuntimeError:
                if time.time() > deadline:
                    raise
                time.sleep(0.5)
        # Drop stale pooled connections to the dead head, then
        # re-attach head-node services (retry while it boots).
        self.head_client.close()
        deadline = time.time() + 15
        while True:
            try:
                self.head_client.call("attach_node_service",
                                      self.node_server.address)
                self.head_client.call("register_node", "head",
                                      self.object_server.address,
                                      self.store_name)
                return
            except Exception:
                if time.time() > deadline:
                    raise
                time.sleep(0.2)

    @property
    def head_address(self) -> str:
        return self._head_address

    def start_worker(self, index: int,
                     resources: Optional[Dict[str, float]] = None,
                     runtime_env: Optional[Dict] = None
                     ) -> str:
        worker_id = f"worker-{index}-{uuid.uuid4().hex[:6]}"
        res = dict(resources or self.resources_per_worker)
        # Only a designated worker may own the TPU; everyone else
        # (including ALL workers when no owner is designated) is forced
        # onto the CPU backend so they can't grab the chip — two
        # workers initializing the TPU backend deadlock on libtpu's
        # single-process lock.
        is_owner = (self.tpu_owner_worker is not None and
                    index == self.tpu_owner_worker)
        if is_owner:
            res.setdefault("TPU", 1.0)
        proc = spawn_worker_process(
            self.head_address, self.store_name, worker_id, res,
            node_id="head", force_cpu_backend=not is_owner,
            runtime_env=runtime_env)
        self.procs[worker_id] = proc
        return worker_id

    def wait_for_workers(self, n: Optional[int] = None,
                         timeout: float = 30) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if n is None:
                # Wait for every live worker process to be registered.
                target = sum(1 for p in self.procs.values()
                             if p.poll() is None)
            else:
                target = n
            alive = [w for w in self.head_client.call("list_workers")
                     if w["alive"]]
            if len(alive) >= target:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"Only {len(self.head_client.call('list_workers'))} of "
            f"{target} workers registered in {timeout}s")

    def kill_worker(self, worker_id: str):
        """Chaos hook: SIGKILL a worker process (the NodeKillerActor
        analogue, python/ray/_private/test_utils.py:1089)."""
        proc = self.procs.get(worker_id)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=10)

    def _monitor_loop(self):
        import traceback

        from ray_tpu.runtime.rpc import RpcError
        while not self._stopped:
            try:
                for worker_id, proc in list(self.procs.items()):
                    if proc.poll() is not None:
                        self.procs.pop(worker_id, None)
                        self.head_client.call("mark_worker_dead",
                                              worker_id)
            except RpcError:
                pass    # head down/restarting: report on next pass
            except Exception:  # noqa: BLE001 — keep monitoring
                traceback.print_exc()
            time.sleep(0.05)

    def stop(self):
        self._stopped = True
        try:
            self.head_client.call("shutdown", timeout=5)
        except Exception:
            pass
        try:
            self.metrics.close()
        except Exception:
            pass
        deadline = time.time() + 3
        for proc in self.procs.values():
            try:
                if proc.poll() is None and time.time() < deadline:
                    proc.terminate()
            except Exception:
                pass
        for proc in self.procs.values():
            try:
                proc.wait(timeout=3)
            except Exception:
                proc.kill()
        try:
            self.head_proc.wait(timeout=3)
        except Exception:
            self.head_proc.kill()
        self.node_server.stop()
        self.object_server.stop()
        self.store.close()
