"""Cluster: the multiprocess test/launch fixture.

Capability parity with the reference's ray.cluster_utils.Cluster
(python/ray/cluster_utils.py:99 add_node — multiple real raylets on one
machine as the primary multi-node test vehicle, SURVEY.md §4.2): real
worker PROCESSES + the C++ shm store + the head scheduler, with
kill-a-worker chaos for fault-tolerance tests.
"""
from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.runtime.client import DistributedRuntime
from ray_tpu.runtime.node import NodeManager


class Cluster:
    def __init__(self, num_workers: int = 2,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 store_capacity: int = 256 * 1024 * 1024,
                 connect: bool = True):
        self.node = NodeManager(num_workers=num_workers,
                                resources_per_worker=resources_per_worker,
                                store_capacity=store_capacity)
        self.agent_procs: Dict[str, object] = {}
        self.node.wait_for_workers(num_workers)
        self.runtime = DistributedRuntime(
            self.node.head_address, self.node.store_name,
            node_manager=self.node)
        self._connected = False
        if connect:
            self.connect()

    def connect(self) -> DistributedRuntime:
        """Install this cluster as the process-global runtime."""
        from ray_tpu._private import worker as worker_mod
        from ray_tpu._private.object_ref import \
            set_global_reference_counter
        if worker_mod.is_initialized():
            if worker_mod._worker.runtime is self.runtime:
                return self.runtime   # already connected: no-op
            worker_mod.shutdown()
        worker_mod._worker = worker_mod.Worker(self.runtime,
                                               mode="driver")
        set_global_reference_counter(self.runtime.ref_counter)
        from ray_tpu._private.object_ref import set_borrow_notifier
        set_borrow_notifier(self.runtime.plane.note_borrow)
        self._connected = True
        return self.runtime

    def add_worker(self, resources: Optional[Dict[str, float]] = None
                   ) -> str:
        index = len(self.node.procs)
        wid = self.node.start_worker(index, resources)
        self.node.wait_for_workers()   # all live processes registered
        return wid

    def add_node(self, num_workers: int = 2,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 store_capacity: int = 256 * 1024 * 1024,
                 timeout: float = 60.0) -> str:
        """Join a SECOND node as a separate process tree with its own
        shm store segment (the multi-raylet `Cluster.add_node` analogue,
        python/ray/cluster_utils.py:165 — here it exercises the real
        cross-node object plane)."""
        import json
        import os
        import subprocess
        import sys
        import time
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        from ray_tpu._private.config import GlobalConfig
        env.update(GlobalConfig.to_env())
        env["JAX_PLATFORMS"] = "cpu"
        alive_before = len([w for w in self.runtime.list_workers()
                            if w["alive"]])
        repo = os.path.abspath(os.path.join(
            os.path.dirname(__file__), "..", ".."))
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.runtime.node_agent",
             "--head", self.node.head_address,
             "--workers", str(num_workers),
             "--resources", json.dumps(resources_per_worker or
                                       {"CPU": 2}),
             "--store-capacity", str(store_capacity)],
            cwd=repo, env=env, stdout=subprocess.PIPE, text=True)
        line = proc.stdout.readline()   # "node_agent ready node_id=..."
        if "node_id=" not in line:
            raise RuntimeError(f"node agent failed to start: {line!r}")
        node_id = line.split("node_id=")[1].split()[0]
        self.agent_procs[node_id] = proc
        deadline = time.time() + timeout
        # Wait for THIS node's workers on top of whatever was already
        # registered cluster-wide (not just the head node's procs —
        # a second add_node would otherwise return early).
        want = num_workers + alive_before
        while time.time() < deadline:
            if len([w for w in self.runtime.list_workers()
                    if w["alive"]]) >= want:
                return node_id
            time.sleep(0.05)
        raise TimeoutError(f"node {node_id}: workers not registered")

    def kill_node(self, node_id: str):
        """SIGKILL a secondary node's whole process tree (agent +
        workers die with it via the agent monitor being gone; worker
        processes are killed explicitly through the head's node table)."""
        proc = self.agent_procs.pop(node_id, None)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=10)
        # The head notices via missed heartbeats; tests shorten the
        # heartbeat config or call mark_node_dead directly for speed.

    def nodes(self):
        return self.runtime.list_nodes()

    def kill_worker(self, worker_id: str):
        self.node.kill_worker(worker_id)

    def start_node_killer(self, interval_s: float = 1.0,
                          max_kills: int = 3,
                          respawn: bool = True) -> "NodeKiller":
        """Chaos: kill a random worker every interval (NodeKillerActor
        analogue, python/ray/_private/test_utils.py:1089)."""
        return NodeKiller(self, interval_s, max_kills, respawn).start()

    def workers(self):
        return self.runtime.list_workers()

    def shutdown(self):
        from ray_tpu._private import worker as worker_mod
        from ray_tpu._private.object_ref import \
            set_global_reference_counter
        if self._connected:
            worker_mod._worker = None
            set_global_reference_counter(None)
            from ray_tpu._private.object_ref import set_borrow_notifier
            set_borrow_notifier(None)
            self._connected = False
        for proc in self.agent_procs.values():
            try:
                proc.terminate()
            except Exception:
                pass
        for proc in self.agent_procs.values():
            try:
                proc.wait(timeout=5)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        self.agent_procs.clear()
        self.runtime.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


class NodeKiller:
    """Kills a random live worker every ``interval_s`` until ``max_kills``
    is reached, optionally respawning a replacement — the chaos vehicle
    for fault-tolerance tests (reference: NodeKillerActor + chaos_test/)."""

    def __init__(self, cluster: Cluster, interval_s: float,
                 max_kills: int, respawn: bool):
        import threading
        self.cluster = cluster
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.respawn = respawn
        self.num_kills = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="node-killer")

    def start(self) -> "NodeKiller":
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)

    def _run(self):
        import random
        while not self._stop.is_set() and self.num_kills < self.max_kills:
            if self._stop.wait(self.interval_s):
                return
            alive = [w["worker_id"]
                     for w in self.cluster.node.head_service.list_workers()
                     if w["alive"]]
            if not alive:
                continue
            victim = random.choice(alive)
            self.cluster.kill_worker(victim)
            self.num_kills += 1
            if self.respawn:
                try:
                    self.cluster.add_worker()
                except Exception:
                    pass
