"""Node agent: joins an existing head as a SECOND node.

Role parity with the reference's non-head raylet (`ray start --address`
→ services.py start_raylet on a worker machine): owns this node's shm
store segment, serves the node's object-plane endpoint, spawns and
monitors this node's worker processes, and heartbeats the head
(GcsHeartbeatManager semantics — the head declares the node dead after
num_heartbeats_timeout missed beats and drops its object locations).

Run: python -m ray_tpu.runtime.node_agent --head H:P --workers N \
         [--token SECRET] [--resources '{"CPU": 2}'] \
         [--store-capacity BYTES] [--node-id ID]

Every RPC connection authenticates with the cluster token the head
minted at startup; a node joining from another machine must present it
via --token or the RAY_TPU_cluster_token environment variable (same
contract as an external driver attach).

Tests use this to build two separate process trees with two store
segments on one machine — the cross-"node" object transfer fixture
(the ray_start_cluster analogue for the object plane).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import uuid
from typing import Dict, Optional

from ray_tpu.runtime.rpc import RpcClient, RpcError, RpcServer


class NodeAgent:
    def __init__(self, head_address: str, num_workers: int = 2,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 store_capacity: int = 256 * 1024 * 1024,
                 node_id: Optional[str] = None):
        self.head_address = head_address
        self.head = RpcClient(head_address, timeout=30)
        self.node_id = node_id or \
            f"node-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self.resources_per_worker = resources_per_worker or {"CPU": 2}
        self.store_name = f"/raytpu_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        from ray_tpu._private.shm_store import ShmObjectStore
        self.store = ShmObjectStore.create(self.store_name,
                                           store_capacity)
        from ray_tpu._private.shm_metrics import ShmMetricsRegistry
        self.metrics = ShmMetricsRegistry.create(self.store_name + "_m")
        from ray_tpu.runtime.object_plane import (ObjectPlane,
                                                  ObjectService,
                                                  prewarm_transfer_path)
        self._service_plane = ObjectPlane(
            self.store, RpcClient(head_address, timeout=30),
            node_id=self.node_id, is_node_service=True)
        self.object_server = RpcServer(
            ObjectService(self.store, plane=self._service_plane))
        self.head.call("register_node", self.node_id,
                       self.object_server.address, self.store_name)
        self._service_plane.multinode = True
        prewarm_transfer_path(self.store, self.object_server.address)
        self.procs: Dict[str, object] = {}
        self._stopped = threading.Event()
        # Owner-driven eager GC: the head broadcasts freed object ids
        # on `object_free`; this node drops its copies immediately
        # (spilled files included) instead of waiting for LRU.
        try:
            from ray_tpu._private.ids import ObjectID
            from ray_tpu.runtime.pubsub import Subscriber
            self._free_sub = Subscriber(RpcClient(head_address))

            def _on_free(_seq, item):
                for oid_hex in item.get("oids", ()):
                    try:
                        self.store.delete(ObjectID.from_hex(oid_hex))
                    except Exception:
                        pass      # not on this node: fine
            self._free_sub.subscribe_stream("object_free", _on_free)
        except Exception:
            self._free_sub = None
        for i in range(num_workers):
            self.start_worker(i)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name=f"agent-monitor-{self.node_id[:12]}")
        self._monitor.start()
        self._beat = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"agent-heartbeat-{self.node_id[:12]}")
        self._beat.start()

    def start_worker(self, index: int,
                     resources: Optional[Dict[str, float]] = None) -> str:
        from ray_tpu.runtime.node import spawn_worker_process
        worker_id = (f"{self.node_id}-worker-{index}-"
                     f"{uuid.uuid4().hex[:6]}")
        proc = spawn_worker_process(
            self.head_address, self.store_name, worker_id,
            dict(resources or self.resources_per_worker),
            node_id=self.node_id,
            # Secondary nodes never own the (single) local TPU.
            force_cpu_backend=True)
        self.procs[worker_id] = proc
        return worker_id

    def wait_for_workers(self, timeout: float = 30) -> None:
        deadline = time.time() + timeout
        want = set(self.procs)
        while time.time() < deadline:
            alive = {w["worker_id"]
                     for w in self.head.call("list_workers")
                     if w["alive"]}
            if want <= alive:
                return
            time.sleep(0.05)
        raise TimeoutError(f"node {self.node_id}: workers not registered")

    def _monitor_loop(self):
        while not self._stopped.is_set():
            for worker_id, proc in list(self.procs.items()):
                if proc.poll() is not None:
                    self.procs.pop(worker_id, None)
                    try:
                        self.head.call("mark_worker_dead", worker_id)
                    except RpcError:
                        pass
            time.sleep(0.05)

    def _heartbeat_loop(self):
        from ray_tpu._private.config import GlobalConfig
        period = GlobalConfig.heartbeat_period_ms / 1000.0
        misses = 0
        from ray_tpu._private.hw_report import collect_hw_stats
        hw_every = max(1, int(2.0 / period))   # hw snapshot ~2s cadence
        beat = 0
        while not self._stopped.wait(timeout=period):
            hw = None
            if beat % hw_every == 0:
                try:
                    hw = collect_hw_stats(self.store)
                except Exception:
                    pass     # reporter is best-effort
            beat += 1
            try:
                ok = self.head.call("node_heartbeat", self.node_id,
                                    hw, timeout=5)
                misses = 0
                if not ok:
                    # Head declared us dead (or restarted): re-join.
                    self.head.call("register_node", self.node_id,
                                   self.object_server.address,
                                   self.store_name)
            except RpcError:
                misses += 1
                if misses >= GlobalConfig.num_heartbeats_timeout:
                    # Head is gone: tear the node down.
                    self.stop()
                    return

    def kill_worker(self, worker_id: str):
        proc = self.procs.get(worker_id)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=10)

    def stop(self):
        if self._stopped.is_set():
            return
        self._stopped.set()
        for proc in self.procs.values():
            try:
                proc.terminate()
            except Exception:
                pass
        for proc in self.procs.values():
            try:
                proc.wait(timeout=3)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        self.object_server.stop()
        try:
            self.metrics.close()
        except Exception:
            pass
        self.store.close()


def main():
    import signal
    ap = argparse.ArgumentParser()
    ap.add_argument("--head", required=True)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--resources", default='{"CPU": 2}')
    ap.add_argument("--store-capacity", type=int,
                    default=256 * 1024 * 1024)
    ap.add_argument("--node-id", default=None)
    ap.add_argument("--token", default=None,
                    help="cluster auth token (defaults to the "
                         "RAY_TPU_cluster_token environment variable)")
    args = ap.parse_args()
    if args.token:
        from ray_tpu._private.config import GlobalConfig
        GlobalConfig.apply_system_config({"cluster_token": args.token})
        # worker processes this agent spawns inherit it via to_env()
        os.environ["RAY_TPU_cluster_token"] = args.token
    agent = NodeAgent(args.head, num_workers=args.workers,
                      resources_per_worker=json.loads(args.resources),
                      store_capacity=args.store_capacity,
                      node_id=args.node_id)
    # Graceful teardown on terminate (tears workers down with us; a
    # SIGKILL is covered by the workers' PR_SET_PDEATHSIG).
    signal.signal(signal.SIGTERM, lambda *_: agent.stop())
    print(f"node_agent ready node_id={agent.node_id} "
          f"store={agent.store_name}", flush=True)
    try:
        while not agent._stopped.wait(timeout=1.0):
            pass
    except KeyboardInterrupt:
        pass
    agent.stop()


if __name__ == "__main__":
    main()
