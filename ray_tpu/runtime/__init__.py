"""Multiprocess (distributed) runtime.

Process layout (single node; the protocol extends to multi-host by running
node managers on each host pointed at one head):

- driver: hosts the HEAD service (control plane + cluster scheduler — the
  GCS + ClusterTaskManager equivalents) and the node manager that spawns
  worker processes and the C++ shm object store.
- workers: separate Python processes; each serves an EXECUTOR endpoint
  (PushTask equivalent), attaches the shm store, executes tasks and hosts
  actors. Nested task submission flows worker -> head scheduler.

Transport: framed-socket RPC (ray_tpu/runtime/rpc.py) — the reference uses
gRPC (src/ray/rpc/); this image lacks grpc python codegen, so the wire layer
is a pluggable length-prefixed protocol behind the same service shapes.
"""
from ray_tpu.runtime.cluster_utils import Cluster

__all__ = ["Cluster"]
