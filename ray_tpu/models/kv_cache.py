"""Paged KV cache: block-pool storage for continuous-batching decode.

vLLM-style paged attention re-thought for TPU/XLA (ref capability:
serve request batching, python/ray/serve/batching.py:46,215 — which
coalesces calls but decodes each batch to completion; this pool is the
structure that lets requests join/leave the decode batch per token):

- The KV pool is ONE static-shape array per layer,
  ``[n_kv_heads, n_pages, page_size, head_dim]`` — XLA never sees a
  dynamic allocation; the host-side ``BlockAllocator`` hands page ids
  to sequences as they grow and reclaims them on completion or
  preemption. The layout is HEAD-MAJOR so one physical page for one
  kv head is a contiguous ``[page_size, head_dim]`` tile — exactly
  what the pallas decode kernel (ops/paged_attention.py) DMAs per
  grid step, and a shape Mosaic can tile (last two dims divisible by
  (8, 128) or full). Page-major ``[n_pages, Pg, KH, D]`` would force
  a (1, Pg, 1, D) block whose sublane dim (1 of KH) Mosaic rejects.
- Page 0 is the NULL page: inactive decode slots point their page
  table at it and harmlessly scatter their dead writes there, so the
  jitted decode step needs no ``lax.cond`` masking — every slot does
  identical work every step (SPMD-friendly, no divergence).
- Gather/scatter use plain advanced indexing: XLA lowers them to
  dynamic-gather/scatter HLO that tiles fine on TPU. A dedicated
  pallas paged-attention kernel can replace the gather later without
  changing this layout.
- ``kv_dtype="int8"`` halves page bytes: pages store int8 with one
  fp32 absmax scale per (kv_head, physical page) — shape
  ``[n_kv_heads, n_pages, 1]`` so the scale shards with its
  head-sharded page column under tensor parallelism. Scales travel
  with page ids: the allocator, prefix cache, and COW path all deal
  in page ids only, and every consumer that moves a page column
  (copy-on-write, placement, donation) moves the matching scale
  column in the same jitted op. Quantize/dequantize live in
  ops/paged_attention.py; nothing outside it interprets the int8
  payload.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import jax.numpy as jnp
import numpy as np

KV_SCALE_DTYPE = jnp.float32


class PagedKVLayer(NamedTuple):
    """Per-layer view of the paged KV pool handed to the attention
    module (a pytree: safe to carry through jit/scan).

    pages_k/pages_v: [n_kv_heads, n_pages, page_size, head_dim]
    page_table:      [n_slots, max_pages] int32 — logical page p of
                     slot s lives in physical page ``page_table[s, p]``
    scales_k/scales_v: [n_kv_heads, n_pages, 1] fp32 per-page absmax
                     scales when the pool is int8, else None. Optional
                     LAST so fp pytrees keep their PR 1–14 structure.
    """
    pages_k: jnp.ndarray
    pages_v: jnp.ndarray
    page_table: jnp.ndarray
    scales_k: Optional[jnp.ndarray] = None
    scales_v: Optional[jnp.ndarray] = None

    @property
    def page_size(self) -> int:
        return self.pages_k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.scales_k is not None


def kv_layer_view(layer, page_table: jnp.ndarray) -> PagedKVLayer:
    """Wrap one engine layer tuple — ``(pk, pv)`` fp or
    ``(pk, pv, sk, sv)`` int8 — as the PagedKVLayer the attention
    module consumes. Keeps the jitted engine builders dtype-agnostic:
    they thread opaque tuples and only this view/store pair knows the
    arity."""
    if len(layer) == 2:
        pk, pv = layer
        return PagedKVLayer(pk, pv, page_table)
    pk, pv, sk, sv = layer
    return PagedKVLayer(pk, pv, page_table, sk, sv)


def kv_layer_store(cache: PagedKVLayer):
    """Inverse of kv_layer_view: the storage tuple (without the shared
    page table) the engine carries between jitted steps."""
    if cache.scales_k is None:
        return (cache.pages_k, cache.pages_v)
    return (cache.pages_k, cache.pages_v,
            cache.scales_k, cache.scales_v)


def init_kv_pool(cfg, n_pages: int, page_size: int,
                 kv_dtype: str = "fp"):
    """One page pool per layer. Page 0 is reserved (null).

    fp:   [(pages_k, pages_v), ...] in cfg.dtype (unchanged layout).
    int8: [(pages_k, pages_v, scales_k, scales_v), ...] — int8 pages
          plus fp32 per-(head, page) absmax scales initialised to 0
          (a 0 scale means "page holds nothing"; paged_append's
          reset-on-offset-0 rule keeps that true across realloc
          without any host-side scale bookkeeping).
    """
    shape = (cfg.n_kv_heads, n_pages, page_size, cfg.head_dim)
    if kv_dtype == "fp":
        return [(jnp.zeros(shape, cfg.dtype),
                 jnp.zeros(shape, cfg.dtype))
                for _ in range(cfg.n_layers)]
    if kv_dtype != "int8":
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
    sshape = (cfg.n_kv_heads, n_pages, 1)
    return [(jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
             jnp.zeros(sshape, KV_SCALE_DTYPE),
             jnp.zeros(sshape, KV_SCALE_DTYPE))
            for _ in range(cfg.n_layers)]


def kv_pool_page_bytes(cfg, page_size: int,
                       kv_dtype: str = "fp") -> int:
    """Bytes ONE physical page costs across all layers (k+v payload
    plus, for int8, its two fp32 scales). The allocator multiplies
    this by occupancy for the bytes view in load/leak reports — the
    number the capacity A/B halves."""
    if kv_dtype == "int8":
        payload = 1
        scale = 2 * cfg.n_kv_heads * 4
    else:
        payload = jnp.dtype(cfg.dtype).itemsize
        scale = 0
    per_layer = 2 * cfg.n_kv_heads * page_size * cfg.head_dim * payload
    return cfg.n_layers * (per_layer + scale)


def export_page_bytes(layers, page: int) -> List[List[bytes]]:
    """Raw bytes of ONE physical page across every layer — the unit a
    cross-replica KV pull ships. Each entry is the layer's column
    tuple serialized in storage order: ``[k, v]`` for fp pools,
    ``[k, v, sk, sv]`` for int8 (the per-page scales TRAVEL WITH the
    payload — a page without its scale is garbage). ``t[:, page]`` is
    the head-major column, so k/v blobs are ``[KH, Pg, D]`` and scale
    blobs ``[KH, 1]``; blocks until any in-flight device computation
    producing ``layers`` has settled."""
    return [[np.asarray(t[:, page]).tobytes() for t in layer]
            for layer in layers]


def page_cols_from_bytes(cfg, page_size: int, kv_dtype: str,
                         blobs: Sequence[Sequence[bytes]]):
    """Inverse of ``export_page_bytes``: rebuild one page's per-layer
    column arrays from raw bytes, shaped for a
    ``pages.at[:, dst].set(col)`` landing — k/v ``[KH, Pg, D]``,
    scales ``[KH, 1]``. Validates arity and byte counts so a
    truncated or cross-dtype blob fails typed instead of landing
    garbage KV."""
    shape = (cfg.n_kv_heads, page_size, cfg.head_dim)
    sshape = (cfg.n_kv_heads, 1)
    if kv_dtype == "int8":
        dts = (np.int8, np.int8,
               np.dtype(KV_SCALE_DTYPE), np.dtype(KV_SCALE_DTYPE))
        shapes = (shape, shape, sshape, sshape)
    elif kv_dtype == "fp":
        dts = (np.dtype(cfg.dtype), np.dtype(cfg.dtype))
        shapes = (shape, shape)
    else:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
    if len(blobs) != cfg.n_layers:
        raise ValueError(
            f"page payload has {len(blobs)} layers, pool has "
            f"{cfg.n_layers}")
    out = []
    for li, layer_blobs in enumerate(blobs):
        if len(layer_blobs) != len(dts):
            raise ValueError(
                f"layer {li}: {len(layer_blobs)} tensors, "
                f"{kv_dtype} pool stores {len(dts)}")
        cols = []
        for b, dt, sh in zip(layer_blobs, dts, shapes):
            want = int(np.prod(sh)) * np.dtype(dt).itemsize
            if len(b) != want:
                raise ValueError(
                    f"layer {li}: {len(b)}-byte tensor, expected "
                    f"{want} for shape {sh} {np.dtype(dt).name}")
            cols.append(np.frombuffer(b, dtype=dt).reshape(sh))
        out.append(tuple(cols))
    return out


class BlockAllocator:
    """Host-side free-list allocator over the physical page pool.

    Page 0 is never handed out — it is the null page inactive slots
    write into. All-or-nothing alloc so a half-grown sequence never
    holds pages it cannot use.

    ``page_bytes`` (optional) is the all-layer byte cost of one page
    (see kv_pool_page_bytes); when set, occupancy gains a bytes view
    so pool_stats/load_report/flight bundles show the memory the
    dtype choice actually buys back.
    """

    def __init__(self, n_pages: int, page_bytes: Optional[int] = None):
        if n_pages < 2:
            raise ValueError("pool needs >= 2 pages (page 0 is null)")
        self.n_pages = n_pages
        self.page_bytes = page_bytes
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._free_set = set(self._free)

    @property
    def n_free(self) -> int:
        return len(self._free)

    def occupancy(self) -> int:
        """Pages currently handed out (the null page never counts).
        At engine quiescence this must equal the prefix cache's
        resident page count — every other page is a leak."""
        return (self.n_pages - 1) - len(self._free)

    def bytes_in_use(self) -> Optional[int]:
        """occupancy() in bytes, or None when page_bytes is unknown."""
        if self.page_bytes is None:
            return None
        return self.occupancy() * self.page_bytes

    def bytes_total(self) -> Optional[int]:
        """Whole-pool byte budget (null page included — it is real
        memory), or None when page_bytes is unknown."""
        if self.page_bytes is None:
            return None
        return self.n_pages * self.page_bytes

    def leak_report(self) -> List[int]:
        """Page ids some owner still holds (not on the free list).
        Diff this against the set of legitimately-held pages (e.g.
        the prefix cache's nodes) to name leaked pages in test
        failures instead of just counting them."""
        return [p for p in range(1, self.n_pages)
                if p not in self._free_set]

    def alloc(self, n: int) -> Optional[List[int]]:
        if n < 0:
            raise ValueError(f"cannot alloc {n} pages")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        return out

    def free(self, pages: Sequence[int]) -> None:
        """Return pages to the free list. Rejects — atomically, before
        any page is accepted — frees of the null page (0), ids outside
        the pool, pages already free (double free), and the same page
        listed twice in one call. Silent acceptance of any of these
        corrupts the pool: the page would later be handed to two
        sequences whose KV scatters then overwrite each other — and
        once the prefix cache shares refcounted pages across
        sequences, a stray free is a cross-REQUEST corruption, not
        just a self-corruption."""
        seen = set()
        for p in pages:
            if not isinstance(p, (int, np.integer)):
                raise ValueError(f"page id {p!r} is not an int")
            if not 0 < p < self.n_pages:
                raise ValueError(
                    f"bad page id {p} (null page 0 and ids >= "
                    f"{self.n_pages} are never freeable)")
            if p in self._free_set:
                raise ValueError(f"double free of page {p}")
            if p in seen:
                raise ValueError(
                    f"page {p} listed twice in one free() call")
            seen.add(p)
        self._free.extend(pages)
        self._free_set.update(pages)
