"""Mixtral-family sparse-MoE transformer: Llama blocks with the FFN
replaced by a top-k routed mixture of SwiGLU experts.

The reference has no model zoo; this family is the expert-parallel
exemplar of the model stack (SURVEY.md §2.4 EP): experts live on an
`expert` mesh axis, tokens dispatch with capacity buffers via dense
einsums (compiler-friendly: no dynamic shapes, XLA lowers the
dispatch/combine einsums onto the MXU and inserts the all-to-alls the
expert sharding implies). Attention/norm/RoPE and the KV-cache decode
path are shared with models/llama.py, so `generate` /
`generate_stream` work unchanged."""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ray_tpu.mesh.sharding import ShardingRules
from ray_tpu.models.llama import (LlamaConfig, block_forward,
                                  transformer_forward)
from ray_tpu.parallel.expert import _maybe_constrain


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    max_seq_len: int = 4096
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336        # per-expert SwiGLU inner dim
    num_experts: int = 8
    num_experts_per_tok: int = 2   # top-k routing (Mixtral: 2)
    capacity_factor: float = 1.25
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def attention_config(self) -> LlamaConfig:
        """The attention stack is exactly Llama's; reuse its module
        with a mirrored config."""
        return LlamaConfig(
            vocab_size=self.vocab_size, max_seq_len=self.max_seq_len,
            dim=self.dim, n_layers=self.n_layers,
            n_heads=self.n_heads, n_kv_heads=self.n_kv_heads,
            hidden_dim=self.hidden_dim, rope_theta=self.rope_theta,
            norm_eps=self.norm_eps, dtype=self.dtype,
            param_dtype=self.param_dtype, remat=self.remat,
            attention_impl=self.attention_impl)


def mixtral_8x7b(**overrides) -> MixtralConfig:
    return MixtralConfig(**overrides)


def mixtral_tiny(**overrides) -> MixtralConfig:
    """Test-size config (GQA + 4 experts top-2) for CPU-mesh tests."""
    d = dict(vocab_size=256, max_seq_len=128, dim=64, n_layers=2,
             n_heads=4, n_kv_heads=2, hidden_dim=128, num_experts=4,
             num_experts_per_tok=2)
    d.update(overrides)
    return MixtralConfig(**d)


class MoEFeedForward(nn.Module):
    """Top-k routed SwiGLU experts with capacity buffers.

    Dense-dispatch formulation (same shape discipline as
    parallel/expert.py SwitchMoE, generalized to top-k): static [E, C]
    capacity buffers, dispatch/combine as einsums, overflow dropped.
    Expert weight tensors carry the `expert` axis for EP sharding."""
    config: MixtralConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        B, T, D = x.shape
        E, K = cfg.num_experts, cfg.num_experts_per_tok
        N = B * T
        # Small token counts (decode steps) run DROP-FREE: worst-case
        # capacity N*K is tiny there, and dropping at T=1 would
        # silently zero expert contributions on routing collisions and
        # change generated tokens. Large N (prefill/training) uses the
        # standard capacity factor.
        if N * K <= 4096:
            C = N * K
        else:
            C = max(K, int(cfg.capacity_factor * K * N / E))

        tokens = x.reshape(N, D)
        router_w = self.param("router", nn.initializers.normal(0.02),
                              (D, E), jnp.float32)
        logits = tokens.astype(jnp.float32) @ router_w        # [N, E]
        # Mixtral normalizes softmax over the selected top-k only.
        topk_logits, topk_idx = jax.lax.top_k(logits, K)      # [N, K]
        topk_gates = jax.nn.softmax(topk_logits, axis=-1)     # [N, K]

        # Capacity slots per (token, choice): position of this
        # assignment within its expert's buffer, counted over the
        # flattened [N*K] assignment stream.
        assign_onehot = jax.nn.one_hot(
            topk_idx.reshape(-1), E, dtype=jnp.int32)         # [N*K, E]
        pos = (jnp.cumsum(assign_onehot, axis=0) - 1) * assign_onehot
        slot = jnp.sum(pos, axis=-1).reshape(N, K)            # [N, K]
        keep = slot < C                                       # overflow

        # dispatch[n, e, c] = sum over kept choices of token n
        disp = (jax.nn.one_hot(topk_idx, E, dtype=cfg.dtype) *
                keep[..., None].astype(cfg.dtype))            # [N,K,E]
        slots = jax.nn.one_hot(slot, C, dtype=cfg.dtype)      # [N,K,C]
        dispatch = jnp.einsum("nke,nkc->nec", disp, slots)    # [N,E,C]
        combine = jnp.einsum(
            "nke,nkc,nk->nec", disp, slots,
            topk_gates.astype(cfg.dtype))                     # [N,E,C]

        pd = cfg.param_dtype
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E, D, cfg.hidden_dim), pd).astype(cfg.dtype)
        w3 = self.param("w3", nn.initializers.lecun_normal(),
                        (E, D, cfg.hidden_dim), pd).astype(cfg.dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E, cfg.hidden_dim, D), pd).astype(cfg.dtype)

        expert_in = jnp.einsum("nd,nec->ecd",
                               tokens.astype(cfg.dtype), dispatch)
        expert_in = _maybe_constrain(expert_in,
                                     P("expert", None, None))
        h = nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, w1)) * \
            jnp.einsum("ecd,edf->ecf", expert_in, w3)
        expert_out = jnp.einsum("ecf,efd->ecd", h, w2)
        expert_out = _maybe_constrain(expert_out,
                                      P("expert", None, None))

        out = jnp.einsum("ecd,nec->nd", expert_out, combine)

        # Load-balance auxiliary (Switch eq. 4 over top-1 choice).
        top1 = jax.nn.one_hot(topk_idx[:, 0], E, dtype=jnp.float32)
        frac_tokens = jnp.mean(top1, axis=0)
        frac_probs = jnp.mean(jax.nn.softmax(logits, axis=-1), axis=0)
        self.sow("losses", "load_balance",
                 E * jnp.sum(frac_tokens * frac_probs))
        return out.reshape(B, T, D)


class MixtralBlock(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x, freqs, positions, kv_cache=None,
                 cache_len=None):
        cfg = self.config
        return block_forward(
            cfg, cfg.attention_config(),
            MoEFeedForward(cfg, name="moe"),
            x, freqs, positions, kv_cache, cache_len)


class Mixtral(nn.Module):
    """Call signature mirrors models/llama.py Llama — enforced by
    construction: both families run the shared transformer_forward, so
    the decode paths (generate / generate_stream, KV caches) apply
    unchanged."""
    config: MixtralConfig

    @nn.compact
    def __call__(self, input_ids, kv_caches=None, cache_len=None):
        return transformer_forward(self, self.config, MixtralBlock,
                                   input_ids, kv_caches, cache_len)


def mixtral_sharding_rules(fsdp: bool = True) -> ShardingRules:
    """Llama TP/FSDP rules + expert-parallel rules for the MoE params:
    expert tensors shard their leading E dim over `expert` and their
    inner dim over `tensor`."""
    f = "fsdp" if fsdp else None
    return ShardingRules([
        (r"attention/w[qkv]/kernel", P(f, "tensor")),
        (r"attention/wo/kernel",     P("tensor", f)),
        (r"moe/w[13]$",              P("expert", f, "tensor")),
        (r"moe/w2$",                 P("expert", "tensor", f)),
        (r"moe/router$",             P(None, None)),
        (r"tok_embeddings$",
         P(("tensor", "fsdp") if fsdp else "tensor", None)),
    ])


def mixtral_tp_validate(cfg: MixtralConfig, tp: int,
                        ep: int = 1) -> None:
    """Check ``cfg`` divides over a ``tp``-way tensor x ``ep``-way
    expert mesh under mixtral_sharding_rules: attention like Llama,
    expert hidden dim over tensor, expert count over expert. Raises
    ValueError naming the offending dimension."""
    from ray_tpu.models.llama import llama_tp_validate
    llama_tp_validate(cfg.attention_config(), tp)
    if ep <= 0:
        raise ValueError(f"ep must be >= 1, got {ep}")
    if cfg.num_experts % ep:
        raise ValueError(
            f"expert parallelism ep={ep} does not divide "
            f"num_experts={cfg.num_experts}")
    if cfg.hidden_dim % tp:
        raise ValueError(
            f"tensor parallelism tp={tp} does not divide expert "
            f"hidden_dim={cfg.hidden_dim}")


def moe_aux_loss(variables) -> jnp.ndarray:
    """Mean load-balance loss over layers (add `mutable=['losses']` to
    apply, then weight this into the training loss)."""
    losses = variables.get("losses", {})
    vals = jax.tree_util.tree_leaves(losses)
    if not vals:
        return jnp.float32(0.0)
    return sum(jnp.asarray(v).mean() for v in vals) / len(vals)


def mixtral_param_count(cfg: MixtralConfig) -> int:
    attn = (cfg.dim * cfg.n_heads * cfg.head_dim +
            2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim +
            cfg.n_heads * cfg.head_dim * cfg.dim)
    moe = cfg.num_experts * 3 * cfg.dim * cfg.hidden_dim + \
        cfg.dim * cfg.num_experts
    per_layer = attn + moe + 2 * cfg.dim
    return cfg.vocab_size * cfg.dim + cfg.n_layers * per_layer + cfg.dim


def active_params_per_token(cfg: MixtralConfig) -> int:
    """Sparse models are priced by ACTIVE params: K experts of E."""
    attn = (cfg.dim * cfg.n_heads * cfg.head_dim +
            2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim +
            cfg.n_heads * cfg.head_dim * cfg.dim)
    moe = cfg.num_experts_per_tok * 3 * cfg.dim * cfg.hidden_dim + \
        cfg.dim * cfg.num_experts
    per_layer = attn + moe + 2 * cfg.dim
    return cfg.vocab_size * cfg.dim + cfg.n_layers * per_layer + cfg.dim
