from ray_tpu.models.bert import (Bert, BertConfig, bert_base,
                                 bert_sharding_rules, bert_tiny,
                                 mask_tokens, mlm_loss)
from ray_tpu.models.t5 import (T5, T5Config, greedy_decode as
                               t5_greedy_decode, seq2seq_loss,
                               t5_sharding_rules, t5_small, t5_tiny)
from ray_tpu.models.gpt2 import (GPT2, GPT2Config, gpt2_sharding_rules,
                                 gpt2_124m)
from ray_tpu.models.llama import (Llama, LlamaConfig, generate,
                                  llama2_7b, llama_sharding_rules,
                                  llama_tiny)
from ray_tpu.models.mixtral import (Mixtral, MixtralConfig,
                                    mixtral_8x7b, mixtral_sharding_rules,
                                    mixtral_tiny, moe_aux_loss)
from ray_tpu.models.resnet import ResNet, ResNetConfig, resnet50, resnet18
from ray_tpu.models.vit import (ViT, ViTConfig, classification_loss,
                                vit_base_16, vit_sharding_rules,
                                vit_tiny)

__all__ = [
    "T5", "T5Config", "t5_small", "t5_tiny", "t5_sharding_rules",
    "t5_greedy_decode", "seq2seq_loss",
    "Bert", "BertConfig", "bert_base", "bert_tiny",
    "bert_sharding_rules", "mask_tokens", "mlm_loss",
    "GPT2", "GPT2Config", "gpt2_sharding_rules", "gpt2_124m",
    "ResNet", "ResNetConfig", "resnet50", "resnet18",
    "ViT", "ViTConfig", "vit_base_16", "vit_tiny",
    "vit_sharding_rules", "classification_loss",
    "Llama", "LlamaConfig", "llama2_7b", "llama_tiny",
    "llama_sharding_rules", "generate",
    "Mixtral", "MixtralConfig", "mixtral_8x7b", "mixtral_tiny",
    "mixtral_sharding_rules", "moe_aux_loss",
]
