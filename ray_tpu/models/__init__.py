from ray_tpu.models.bert import (Bert, BertConfig, bert_base,
                                 bert_sharding_rules, bert_tiny,
                                 mask_tokens, mlm_loss)
from ray_tpu.models.gpt2 import (GPT2, GPT2Config, gpt2_sharding_rules,
                                 gpt2_124m)
from ray_tpu.models.llama import (Llama, LlamaConfig, generate,
                                  llama2_7b, llama_sharding_rules,
                                  llama_tiny)
from ray_tpu.models.mixtral import (Mixtral, MixtralConfig,
                                    mixtral_8x7b, mixtral_sharding_rules,
                                    mixtral_tiny, moe_aux_loss)
from ray_tpu.models.resnet import ResNet, ResNetConfig, resnet50, resnet18

__all__ = [
    "Bert", "BertConfig", "bert_base", "bert_tiny",
    "bert_sharding_rules", "mask_tokens", "mlm_loss",
    "GPT2", "GPT2Config", "gpt2_sharding_rules", "gpt2_124m",
    "ResNet", "ResNetConfig", "resnet50", "resnet18",
    "Llama", "LlamaConfig", "llama2_7b", "llama_tiny",
    "llama_sharding_rules", "generate",
    "Mixtral", "MixtralConfig", "mixtral_8x7b", "mixtral_tiny",
    "mixtral_sharding_rules", "moe_aux_loss",
]
