"""Llama-family transformer in flax, mesh-first, with a jitted
KV-cache generation loop.

The reference has no model zoo; this family exists for the build's
serving north star (BASELINE.md: "Serve Llama-2-7B JAX replicas
autoscaled on v5e") and as the GQA/RoPE/SwiGLU exemplar of the model
stack. TPU design mirrors models/gpt2.py: bf16 matmuls with fp32
norms/logits, MXU-friendly dims, sharding declared as logical-axis
rules (Megatron TP + FSDP), pallas/XLA attention via ray_tpu.ops.
Decode uses a static-shape KV cache updated with dynamic_update_slice
inside one jitted lax.while_loop — no per-token retrace.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ray_tpu.mesh.sharding import ShardingRules
from ray_tpu.models.kv_cache import PagedKVLayer
from ray_tpu.ops.paged_attention import paged_decode_attention


def _use_paged_kernel() -> bool:
    """Paged decode attention backend: default is the XLA gather.
    Measured on a v5e chip, 1.1B bf16, 16 slots, L=256, full decode
    step (dense floor 3.5ms): standalone the pallas kernel wins at
    page_size 64 (3.6ms vs gather 8.2ms), but INSIDE the engine's
    donated decode loop the ranking flips — gather steps run at
    4.2ms (XLA aliases the pool update in place across iterations)
    while the kernel steps run at 7.5ms: the pallas custom call
    defeats the loop-carry aliasing of the 67MB/layer pools and
    buys a full pool copy per step. Re-examined under the engine's
    OVERLAPPED hot loop (serve_bench.py --overlap-ab --paged-kernel):
    the ranking does NOT flip back — overlap hides host readback
    latency behind device compute, but the aliasing defeat is a
    compile-time property of the dispatched computation itself, so
    the per-step pool copy is still paid on-device where no amount
    of host overlap can cover it. Until that aliasing is proven
    through the custom call, the gather is the right default on
    every backend; RAY_TPU_PAGED_KERNEL=1 forces the kernel (and
    =0 forces the gather) for experiments and tests. Junk values
    raise EnvKnobError (util/envknobs.py) instead of silently
    picking the default — a typo here would invalidate a whole
    perf-triage session."""
    from ray_tpu.util.envknobs import parse_paged_kernel_env
    return parse_paged_kernel_env(default=False)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 4096
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32          # < n_heads => grouped-query attention
    hidden_dim: int = 11008       # SwiGLU inner dim
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def llama2_7b(**overrides) -> LlamaConfig:
    return LlamaConfig(**overrides)


def llama_tiny(**overrides) -> LlamaConfig:
    """Test-size config for CPU-mesh tests (GQA exercised: 4 q heads,
    2 kv heads)."""
    d = dict(vocab_size=256, max_seq_len=128, dim=64, n_layers=2,
             n_heads=4, n_kv_heads=2, hidden_dim=128)
    d.update(overrides)
    return LlamaConfig(**d)


# --------------------------------------------------------------------------
# RoPE
# --------------------------------------------------------------------------

def rope_freqs(head_dim: int, max_len: int, theta: float) -> jnp.ndarray:
    inv = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    return jnp.outer(t, inv)   # [max_len, head_dim/2]


def apply_rope(x: jnp.ndarray, freqs: jnp.ndarray,
               positions: jnp.ndarray) -> jnp.ndarray:
    """x: [B, T, H, D]; positions: [T] or [B, T]."""
    f = freqs[positions]                       # [..., T, D/2]
    if f.ndim == 2:
        f = f[None]                            # [1, T, D/2]
    cos = jnp.cos(f)[..., None, :]             # [B|1, T, 1, D/2]
    sin = jnp.sin(f)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        xf = x.astype(jnp.float32)
        norm = xf * jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, freqs, positions, kv_cache=None,
                 cache_len=None):
        cfg = self.config
        B, T, _ = x.shape
        hd = cfg.head_dim
        q = nn.Dense(cfg.n_heads * hd, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="wq")(x)
        k = nn.Dense(cfg.n_kv_heads * hd, use_bias=False,
                     dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="wk")(x)
        v = nn.Dense(cfg.n_kv_heads * hd, use_bias=False,
                     dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="wv")(x)
        q = q.reshape(B, T, cfg.n_heads, hd)
        k = k.reshape(B, T, cfg.n_kv_heads, hd)
        v = v.reshape(B, T, cfg.n_kv_heads, hd)
        q = apply_rope(q, freqs, positions)
        k = apply_rope(k, freqs, positions)

        new_cache = None
        if isinstance(kv_cache, PagedKVLayer):
            # Paged attention (continuous batching) with per-slot
            # positions. T == 1 is the decode step; T > 1 is a
            # chunked-prefill chunk whose tokens APPEND AT OFFSET
            # (possibly mid-page, possibly spanning pages). Scatter
            # this chunk's K/V into the slots' pages, then attend
            # each query over its slot's gathered page window under
            # a causal mask on absolute positions. Inactive slots
            # carry page_table rows of 0 (the null page) — their
            # writes land there and their outputs are ignored
            # host-side, so no lax.cond is needed.
            pc = kv_cache
            pos = cache_len                       # [B] int32
            Pg = pc.page_size
            from ray_tpu.ops.paged_attention import paged_append
            if pc.quantized:
                # int8 pool: append quantizes in place and returns
                # updated per-page scales, which travel WITH the
                # pages through the cache pytree (COW, donation,
                # placement all move them together).
                pk, pv, sk, sv = paged_append(
                    pc.pages_k, pc.pages_v, pc.page_table, pos, k, v,
                    pc.scales_k, pc.scales_v)
                new_cache = pc._replace(pages_k=pk, pages_v=pv,
                                        scales_k=sk, scales_v=sv)
            else:
                pk, pv = paged_append(pc.pages_k, pc.pages_v,
                                      pc.page_table, pos, k, v)
                sk = sv = None
                new_cache = pc._replace(pages_k=pk, pages_v=pv)
            if T == 1 and _use_paged_kernel():
                # TPU decode: pallas paged-attention kernel — page
                # table rides scalar prefetch; the page window is
                # never materialized (ops/paged_attention.py). Int8
                # pages dequantize in-register inside the kernel.
                y = paged_decode_attention(
                    q[:, 0], pk, pv, pc.page_table, pos, sk, sv)
                y = y.reshape(B, 1, cfg.n_heads, hd)
            else:
                # CPU/XLA fallback and chunk prefill: gather the page
                # window dense. [KH, B, max_pages, Pg, D] ->
                # [KH, B, L, D]; gathered index == logical sequence
                # position by construction.
                L = pc.page_table.shape[1] * Pg
                kg = pk[:, pc.page_table]
                vg = pv[:, pc.page_table]
                if sk is not None:
                    # dequantize the gathered window in fp32 using the
                    # gathered per-page scales (value = q * s / 127) —
                    # only the per-step [B, L] window ever exists in
                    # fp, never the pool itself
                    skg = sk[:, pc.page_table]  # [KH, B, MP, 1]
                    svg = sv[:, pc.page_table]
                    kg = kg.astype(jnp.float32) * \
                        (skg * (1.0 / 127.0))[..., None]
                    vg = vg.astype(jnp.float32) * \
                        (svg * (1.0 / 127.0))[..., None]
                kg = kg.reshape(cfg.n_kv_heads, B, L, hd)
                vg = vg.reshape(cfg.n_kv_heads, B, L, hd)
                # Grouped-query attention WITHOUT materializing
                # repeated K/V: q reshapes to [B, T, KH, rep, D] and
                # contracts against the grouped cache directly — at
                # rep=8 (1.1B) a repeat would move 8x the KV bytes
                # per step, the decode hot loop's dominant traffic.
                rep = cfg.n_heads // cfg.n_kv_heads
                qg = q.reshape(B, -1, cfg.n_kv_heads, rep, hd)
                scores = jnp.einsum(
                    "btkrd,kbsd->bkrts", qg.astype(jnp.float32),
                    kg.astype(jnp.float32)) / np.sqrt(hd)
                # causal over absolute positions: query t of slot b
                # sits at pos[b] + t and sees keys 0..pos[b]+t
                q_pos = pos[:, None] + jnp.arange(T)[None]   # [B, T]
                valid = jnp.arange(L)[None, None] <= \
                    q_pos[:, :, None]                        # [B, T, L]
                scores = jnp.where(valid[:, None, None],
                                   scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1)
                y = jnp.einsum("bkrts,kbsd->btkrd",
                               probs.astype(vg.dtype), vg)
                y = y.reshape(B, -1, cfg.n_heads, hd)
        elif kv_cache is not None:
            # Decode path: append this step's K/V into the static cache.
            ck, cv = kv_cache
            ck = jax.lax.dynamic_update_slice(
                ck, k.astype(ck.dtype), (0, cache_len, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cv, v.astype(cv.dtype), (0, cache_len, 0, 0))
            new_cache = (ck, cv)
            k, v = ck, cv
            S = k.shape[1]
            # Mask out positions beyond cache_len + T.
            kv_pos = jnp.arange(S)
            valid = kv_pos < (cache_len + T)
            # grouped-query contraction (no repeated-K/V copy; see
            # the paged branch above)
            rep = cfg.n_heads // cfg.n_kv_heads
            qg = q.reshape(B, T, cfg.n_kv_heads, rep, hd)
            scores = jnp.einsum(
                "btkrd,bskd->bkrts", qg.astype(jnp.float32),
                k.astype(jnp.float32)) / np.sqrt(hd)
            q_pos = cache_len + jnp.arange(T)
            causal = kv_pos[None, :] <= q_pos[:, None]
            mask = (causal & valid[None, :])[None, None, None]
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            y = jnp.einsum("bkrts,bskd->btkrd",
                           probs.astype(v.dtype), v)
            y = y.reshape(B, T, cfg.n_heads, hd)
        else:
            rep = cfg.n_heads // cfg.n_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
            from ray_tpu.ops.attention import multi_head_attention
            y = multi_head_attention(q, k, v, causal=True,
                                     impl=cfg.attention_impl)
        y = y.reshape(B, T, cfg.n_heads * hd)
        out = nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wo")(y)
        return out, new_cache


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        gate = nn.Dense(cfg.hidden_dim, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="w1")(x)
        up = nn.Dense(cfg.hidden_dim, use_bias=False, dtype=cfg.dtype,
                      param_dtype=cfg.param_dtype, name="w3")(x)
        return nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="w2")(
            nn.silu(gate) * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, freqs, positions, kv_cache=None,
                 cache_len=None):
        cfg = self.config
        return block_forward(
            cfg, cfg, LlamaMLP(cfg, name="feed_forward"),
            x, freqs, positions, kv_cache, cache_len)


def transformer_forward(mod: nn.Module, cfg, block_cls, input_ids,
                        kv_caches=None, cache_len=None):
    """Shared decoder-transformer body (embedding, RoPE table,
    position/cache plumbing, layer loop, final norm, tied logits).
    Every Llama-shaped family (Llama, Mixtral) calls this with its own
    block class, so the decode contract `generate`/`generate_stream`
    rely on cannot drift per family. Called from a compact __call__:
    submodules bind into the caller's scope."""
    B, T = input_ids.shape
    tok = mod.param("tok_embeddings",
                    nn.initializers.normal(0.02),
                    (cfg.vocab_size, cfg.dim), cfg.param_dtype)
    x = tok[input_ids].astype(cfg.dtype)
    freqs = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    if cache_len is None:
        positions = jnp.arange(T)
    elif jnp.ndim(cache_len) == 1:
        # Per-slot positions (paged continuous-batching decode):
        # [B] + [T] -> [B, T]; apply_rope handles batched positions.
        positions = cache_len[:, None] + jnp.arange(T)[None]
    else:
        positions = cache_len + jnp.arange(T)
    block = block_cls
    if cfg.remat:
        block = nn.remat(block_cls, static_argnums=())
    new_caches = []
    for i in range(cfg.n_layers):
        cache_i = None if kv_caches is None else kv_caches[i]
        x, nc = block(cfg, name=f"layers_{i}")(
            x, freqs, positions, cache_i, cache_len)
        new_caches.append(nc)
    x = RMSNorm(cfg.norm_eps, name="norm")(x)
    logits = jax.lax.dot_general(
        x.astype(cfg.dtype), tok.astype(cfg.dtype),
        (((2,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if kv_caches is None:
        return logits, None
    return logits, new_caches


def block_forward(cfg, attn_cfg, ffn_module, x, freqs, positions,
                  kv_cache=None, cache_len=None):
    """Shared pre-norm block body: attention residual + FFN residual.
    The FFN module is the only thing that varies across families."""
    h, new_cache = LlamaAttention(attn_cfg, name="attention")(
        RMSNorm(cfg.norm_eps, name="attention_norm")(x),
        freqs, positions, kv_cache, cache_len)
    x = x + h
    x = x + ffn_module(RMSNorm(cfg.norm_eps, name="ffn_norm")(x))
    return x, new_cache


class Llama(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, kv_caches=None, cache_len=None):
        """Returns (logits, new_kv_caches). kv_caches: list per layer of
        (k, v) arrays [B, max_seq, n_kv_heads, head_dim]."""
        return transformer_forward(self, self.config, LlamaBlock,
                                   input_ids, kv_caches, cache_len)


def init_kv_caches(cfg: LlamaConfig, batch: int, max_len: int):
    return [
        (jnp.zeros((batch, max_len, cfg.n_kv_heads, cfg.head_dim),
                   cfg.dtype),
         jnp.zeros((batch, max_len, cfg.n_kv_heads, cfg.head_dim),
                   cfg.dtype))
        for _ in range(cfg.n_layers)]


_CACHE_CAP = 32       # compiled decode variants kept per process


def _cache_get(cache: "collections.OrderedDict", key):
    """Bounded LRU for compiled decode closures: long-lived serving
    replicas see many (batch, prompt-length) shapes; unbounded caching
    would pin every jit executable + model closure forever."""
    hit = cache.get(key)
    if hit is not None:
        cache.move_to_end(key)
    return hit


def _cache_put(cache: "collections.OrderedDict", key, value):
    cache[key] = value
    cache.move_to_end(key)
    while len(cache) > _CACHE_CAP:
        cache.popitem(last=False)


import collections

_DECODE_CACHE: "collections.OrderedDict" = collections.OrderedDict()


def generate(model: Llama, params, prompt_ids: jnp.ndarray,
             max_new_tokens: int, temperature: float = 0.0,
             rng: Optional[jax.Array] = None,
             eos_id: Optional[int] = None) -> jnp.ndarray:
    """Jitted autoregressive decode: one prefill call, then a
    lax.while_loop of single-token steps over a static KV cache. The
    jitted function is cached per (config, batch, prompt_len,
    max_new_tokens, temperature, eos) so repeated calls — e.g. serve
    requests — reuse one compilation.
    """
    cfg = model.config
    B, T0 = prompt_ids.shape
    total = T0 + max_new_tokens
    if rng is None:
        rng = jax.random.PRNGKey(0)
    cache_key = (cfg, B, T0, max_new_tokens, temperature, eos_id)
    cached = _cache_get(_DECODE_CACHE, cache_key)
    if cached is not None:
        return cached(params, prompt_ids, rng)

    @jax.jit
    def _decode(params, prompt_ids, rng):
        caches = init_kv_caches(cfg, B, total)
        logits, caches = model.apply(params, prompt_ids,
                                     kv_caches=caches, cache_len=0)
        tokens = jnp.zeros((B, total), jnp.int32)
        tokens = jax.lax.dynamic_update_slice(tokens, prompt_ids, (0, 0))

        first = _pick_token(logits[:, -1], rng, temperature)
        tokens = jax.lax.dynamic_update_slice(
            tokens, first[:, None], (0, T0))

        def cond(state):
            i, _tokens, _caches, _key, done_rows = state
            return (i < max_new_tokens) & ~jnp.all(done_rows)

        def body(state):
            i, tokens, caches, key, done_rows = state
            key, sub = jax.random.split(key)
            cur = jax.lax.dynamic_slice(tokens, (0, T0 + i - 1),
                                        (B, 1))
            logits, caches = model.apply(
                params, cur, kv_caches=caches, cache_len=T0 + i - 1)
            nxt = _pick_token(logits[:, -1], sub, temperature)
            tokens = jax.lax.dynamic_update_slice(
                tokens, nxt[:, None], (0, T0 + i))
            if eos_id is not None:
                # Per-row flags track only tokens actually sampled, so
                # the zero-filled tail never counts and eos_id may
                # legitimately be 0.
                done_rows = done_rows | (nxt == eos_id)
            return (i + 1, tokens, caches, key, done_rows)

        done0 = (first == eos_id) if eos_id is not None \
            else jnp.zeros((B,), jnp.bool_)
        state = (jnp.int32(1), tokens, caches, rng, done0)
        _, tokens, _, _, _ = jax.lax.while_loop(cond, body, state)
        return tokens

    _cache_put(_DECODE_CACHE, cache_key, _decode)
    return _decode(params, prompt_ids, rng)


_STREAM_CACHE: "collections.OrderedDict" = collections.OrderedDict()


def generate_stream(model: Llama, params, prompt_ids: jnp.ndarray,
                    max_new_tokens: int, temperature: float = 0.0,
                    rng: Optional[jax.Array] = None,
                    eos_id: Optional[int] = None,
                    chunk_size: int = 8):
    """Incremental decode for streaming serving: a jitted prefill plus
    a jitted lax.scan of ``chunk_size`` single-token steps. Yields each
    batch-row's next token as a numpy int32 array of shape [B], in
    bursts of up to ``chunk_size``.

    Why chunked: a host readback pays the runtime's completion-
    notification latency (tens of ms on tunneled devices) REGARDLESS
    of compute size, so syncing per token caps streaming at ~1/latency
    tokens/s. One scan dispatch + one [K, B] readback amortizes that
    latency over K tokens while keeping time-to-first-token at one
    prefill + one sync. The whole-sequence `generate` (on-device
    while_loop) remains the fastest path for full completions.
    (Reference capability: serve streaming responses,
    python/ray/serve/api.py streaming + _private/http_util.py chunked
    responses.)"""
    cfg = model.config
    B, T0 = prompt_ids.shape
    K = max(1, min(chunk_size, max_new_tokens))
    n_chunks = (max_new_tokens - 1 + K - 1) // K
    total = T0 + 1 + n_chunks * K    # cache covers whole-K chunks
    if rng is None:
        rng = jax.random.PRNGKey(0)

    key = (cfg, B, T0, K, n_chunks, temperature)
    cached = _cache_get(_STREAM_CACHE, key)
    if cached is None:
        @jax.jit
        def _prefill(params, prompt_ids, rng):
            caches = init_kv_caches(cfg, B, total)
            logits, caches = model.apply(params, prompt_ids,
                                         kv_caches=caches, cache_len=0)
            first = _pick_token(logits[:, -1], rng, temperature)
            return first, caches

        @jax.jit
        def _chunk(params, cur, caches, cache_len, rng):
            def body(carry, i):
                cur, caches, key = carry
                key, sub = jax.random.split(key)
                logits, caches = model.apply(
                    params, cur[:, None], kv_caches=caches,
                    cache_len=cache_len + i)
                nxt = _pick_token(logits[:, -1], sub, temperature)
                return (nxt, caches, key), nxt
            (cur, caches, rng), toks = jax.lax.scan(
                body, (cur, caches, rng), jnp.arange(K))
            return toks, cur, caches      # toks: [K, B]

        cached = (_prefill, _chunk)
        _cache_put(_STREAM_CACHE, key, cached)
    _prefill, _chunk = cached

    rng, sub = jax.random.split(rng)
    tok, caches = _prefill(params, prompt_ids, sub)
    first = np.asarray(tok)
    done = np.zeros((B,), bool)
    if eos_id is not None:
        done |= (first == eos_id)
    yield first
    emitted = 1
    for c in range(n_chunks):
        if emitted >= max_new_tokens or \
                (eos_id is not None and done.all()):
            return
        rng, sub = jax.random.split(rng)
        # the chunk's first step consumes the last emitted token, which
        # sits at position T0 + emitted - 1
        toks, tok, caches = _chunk(params, tok, caches,
                                   jnp.int32(T0 + emitted - 1), sub)
        out = np.asarray(toks)           # ONE sync per K tokens
        for j in range(out.shape[0]):
            if emitted >= max_new_tokens:
                return
            row = out[j]
            if eos_id is not None:
                done |= (row == eos_id)
            yield row
            emitted += 1
            if eos_id is not None and done.all():
                return


def _pick_token(logits_last, key, temperature: float):
    if temperature <= 0.0:
        return jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, logits_last / temperature, axis=-1).astype(jnp.int32)


def llama_sharding_rules(fsdp: bool = True) -> ShardingRules:
    """Megatron TP + FSDP rules for flax Llama params.

    Column-parallel: wq/wk/wv, w1/w3. Row-parallel: wo, w2.
    Embeddings are vocab-parallel over (tensor, fsdp) with the model dim
    unsharded — sharding the model dim of tok_embeddings over fsdp
    forces an involuntary-full-remat reshard of the embedding gradient
    on dp x fsdp x tp meshes (see gpt2_sharding_rules).
    """
    f = "fsdp" if fsdp else None
    return ShardingRules([
        (r"attention/w[qkv]/kernel", P(f, "tensor")),
        (r"attention/wo/kernel",     P("tensor", f)),
        (r"feed_forward/w[13]/kernel", P(f, "tensor")),
        (r"feed_forward/w2/kernel",  P("tensor", f)),
        (r"tok_embeddings$",
         P(("tensor", "fsdp") if fsdp else "tensor", None)),
    ])


def llama_tp_validate(cfg: LlamaConfig, tp: int) -> None:
    """Check that ``cfg`` divides evenly over a ``tp``-way tensor mesh
    under llama_sharding_rules: heads and kv heads (head-sharded
    attention + KV pool), hidden_dim (column/row-parallel MLP), and
    vocab (vocab-parallel embedding / tied logits). Raises ValueError
    naming the offending dimension — GSPMD would otherwise pad or
    fall back to unexpected reshards silently."""
    if tp <= 0:
        raise ValueError(f"tp must be >= 1, got {tp}")
    for what, n in (("n_heads", cfg.n_heads),
                    ("n_kv_heads", cfg.n_kv_heads),
                    ("hidden_dim", cfg.hidden_dim),
                    ("vocab_size", cfg.vocab_size)):
        if n % tp:
            raise ValueError(
                f"tensor parallelism tp={tp} does not divide "
                f"{what}={n} for this Llama config")


def llama_param_count(cfg: LlamaConfig) -> int:
    per_layer = (cfg.dim * cfg.n_heads * cfg.head_dim +
                 2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim +
                 cfg.n_heads * cfg.head_dim * cfg.dim +
                 3 * cfg.dim * cfg.hidden_dim + 2 * cfg.dim)
    return (cfg.vocab_size * cfg.dim + cfg.n_layers * per_layer +
            cfg.dim)


def llama_flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs per token: the standard 6N matmul estimate
    (fwd 2N + bwd 4N) plus the attention-score term 12·L·H·hd·T that
    6N misses because QK^T/AV scale with sequence length, not param
    count. This is the denominator MFU is quoted against (PaLM
    appendix B convention), so bench MFU numbers are comparable to
    published ones."""
    return (6.0 * llama_param_count(cfg) +
            12.0 * cfg.n_layers * cfg.n_heads * cfg.head_dim * seq_len)
