"""ViT: vision transformer for image classification, TPU-native flax.

Widens the vision side of models/ beyond ResNet (the reference
framework ships no models; BASELINE.md's vision obligation is
image-classification train/predict throughput, which ResNet covers —
ViT adds the patchify-encoder shape that dominates modern image
fleets and maps straight onto the MXU: the patch embedding is one
strided conv, everything after is the same dense encoder stack as
BERT). Same conventions as bert.py/gpt2.py: fp32 LayerNorms around
cfg.dtype matmuls, attention through ops.attention, sharding declared
as logical-axis rules.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.mesh.sharding import ShardingRules


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    num_classes: int = 1000
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    dropout: float = 0.0
    pool: str = "cls"            # "cls" token or "mean" of patches
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def vit_base_16(**overrides) -> ViTConfig:
    return ViTConfig(**overrides)


def vit_tiny(**overrides) -> ViTConfig:
    d = dict(image_size=32, patch_size=8, num_classes=10, dim=64,
             n_layers=2, n_heads=2, hidden_dim=128,
             dtype=jnp.float32)
    d.update(overrides)
    return ViTConfig(**d)


class ViTBlock(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        B, T, C = x.shape
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x)
        qkv = nn.Dense(3 * cfg.dim, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype,
                       name="qkv")(h.astype(cfg.dtype))
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, cfg.n_heads, cfg.head_dim)

        from ray_tpu.ops.attention import multi_head_attention
        a = multi_head_attention(heads(q), heads(k), heads(v),
                                 causal=False,
                                 impl=cfg.attention_impl)
        a = nn.Dense(cfg.dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     name="out")(a.reshape(B, T, C))
        if cfg.dropout > 0:
            a = nn.Dropout(cfg.dropout)(a, deterministic=deterministic)
        x = x + a
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_ffn")(x)
        h = nn.Dense(cfg.hidden_dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     name="ffn_in")(h.astype(cfg.dtype))
        h = nn.gelu(h)
        h = nn.Dense(cfg.dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="ffn_out")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return x + h


class ViT(nn.Module):
    """Patchify -> pre-LN encoder -> pooled classification logits.

    __call__(images[B,H,W,C]) -> logits [B, num_classes] (fp32).
    """
    config: ViTConfig

    @nn.compact
    def __call__(self, images, deterministic: bool = True):
        cfg = self.config
        B = images.shape[0]
        # Patch embedding = one strided conv: the [P,P,C]->dim
        # projection is a single big matmul per patch grid on the MXU.
        x = nn.Conv(cfg.dim,
                    kernel_size=(cfg.patch_size, cfg.patch_size),
                    strides=(cfg.patch_size, cfg.patch_size),
                    padding="VALID", dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype,
                    name="patch_embed")(images.astype(cfg.dtype))
        x = x.reshape(B, -1, cfg.dim)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.dim), cfg.param_dtype)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (B, 1, cfg.dim)).astype(x.dtype),
             x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(0.02),
                         (1, cfg.num_patches + 1, cfg.dim),
                         cfg.param_dtype)
        x = x + pos.astype(x.dtype)
        for i in range(cfg.n_layers):
            x = ViTBlock(cfg, name=f"block_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        if cfg.pool == "mean":
            pooled = x[:, 1:].mean(axis=1)
        else:
            pooled = x[:, 0]
        logits = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                          param_dtype=cfg.param_dtype,
                          name="head")(pooled.astype(jnp.float32))
        return logits


def classification_loss(logits, labels):
    """Mean softmax cross-entropy over int labels."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    gold = jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return -gold.mean()


def vit_sharding_rules(fsdp: bool = True) -> ShardingRules:
    """Megatron-style TP + optional FSDP for the encoder (same stance
    as bert_sharding_rules: qkv/ffn_in column-parallel, out/ffn_out
    row-parallel; patch embed and head are small — fsdp-only)."""
    f = "fsdp" if fsdp else None
    return ShardingRules([
        (r"patch_embed/kernel$", P(None, None, None, f)),
        (r"(cls_token|pos_embed)$", P(None, None, None)),
        (r"(qkv|ffn_in)/kernel$", P(f, "tensor")),
        (r"(out|ffn_out)/kernel$", P("tensor", f)),
        (r"head/kernel$", P(f, None)),
        (r"bias$", P(None)),
        (r"(ln_\w+|scale)$", P(None)),
        (r".*", P(None)),
    ])
