"""BERT: bidirectional encoder + masked-LM head, TPU-native flax.

The encoder model family widening models/ beyond decoders (the
reference framework ships no models; BASELINE.md's model obligations
are decoder-LM training/serving, which GPT-2/Llama/Mixtral cover —
BERT adds the encoder/MLM shape of embedding and classification
fleets). Same conventions as gpt2.py: fp32 LayerNorms around
cfg.dtype matmuls, attention through ops.attention (padding handled
as an additive bias so the pallas flash path stays available for
unmasked batches), sharding declared as logical-axis rules.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.mesh.sharding import ShardingRules


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def bert_base(**overrides) -> BertConfig:
    return BertConfig(**overrides)


def bert_tiny(**overrides) -> BertConfig:
    d = dict(vocab_size=1024, max_position_embeddings=128, dim=128,
             n_layers=2, n_heads=2, hidden_dim=256,
             dtype=jnp.float32)
    d.update(overrides)
    return BertConfig(**d)


from ray_tpu.ops.attention import padding_bias as _padding_bias


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, bias=None, deterministic: bool = True):
        cfg = self.config
        B, T, C = x.shape
        qkv = nn.Dense(3 * cfg.dim, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, cfg.n_heads, cfg.head_dim)

        from ray_tpu.ops.attention import multi_head_attention
        y = multi_head_attention(heads(q), heads(k), heads(v),
                                 causal=False, impl=cfg.attention_impl,
                                 bias=bias)
        y = y.reshape(B, T, C)
        y = nn.Dense(cfg.dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="out")(y)
        if cfg.dropout > 0:
            y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return y


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, bias=None, deterministic: bool = True):
        cfg = self.config
        # Post-LN, the BERT arrangement (vs GPT's pre-LN).
        a = BertSelfAttention(cfg, name="attn")(
            x.astype(cfg.dtype), bias, deterministic)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x + a)
        h = nn.Dense(cfg.hidden_dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     name="ffn_in")(x.astype(cfg.dtype))
        h = nn.gelu(h)
        h = nn.Dense(cfg.dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="ffn_out")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return nn.LayerNorm(dtype=jnp.float32, name="ln_ffn")(x + h)


class Bert(nn.Module):
    """Encoder trunk + heads.

    __call__(input_ids, token_type_ids=None, attention_mask=None)
    returns the final hidden states [B, T, dim] (fp32);
    return_mlm_logits=True ties the decoder to the word embedding;
    return_pooled=True returns (hidden, pooled) where pooled is the
    tanh-projected [CLS] vector (init with the flags you will apply
    with — flax creates only the traced params).
    """

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None,
                 attention_mask=None, deterministic: bool = True,
                 return_mlm_logits: bool = False,
                 return_pooled: bool = False):
        cfg = self.config
        B, T = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        wpe = self.param("wpe", nn.initializers.normal(0.02),
                         (cfg.max_position_embeddings, cfg.dim),
                         cfg.param_dtype)
        wtt = self.param("wtt", nn.initializers.normal(0.02),
                         (cfg.type_vocab_size, cfg.dim),
                         cfg.param_dtype)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = wte[input_ids] + wpe[jnp.arange(T)][None] + \
            wtt[token_type_ids]
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_emb")(x)
        bias = None
        if attention_mask is not None:
            bias = _padding_bias(attention_mask)
        for i in range(cfg.n_layers):
            x = BertLayer(cfg, name=f"layer_{i}")(x, bias,
                                                  deterministic)
        if return_pooled:
            cls = x[:, 0].astype(cfg.dtype)
            pooled = jnp.tanh(
                nn.Dense(cfg.dim, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         name="pooler")(cls))
            return x, pooled
        if not return_mlm_logits:
            return x
        # Tied MLM head (transform + decode against wte^T).
        h = nn.Dense(cfg.dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="mlm_dense")(
            x.astype(cfg.dtype))
        h = nn.gelu(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="mlm_ln")(h)
        logits = jnp.einsum("btd,vd->btv", h.astype(cfg.dtype),
                            wte.astype(cfg.dtype))
        logits = logits + self.param(
            "mlm_bias", nn.initializers.zeros, (cfg.vocab_size,),
            cfg.param_dtype)
        return logits

def mlm_loss(logits, labels, ignore_index: int = -100):
    """Masked-LM cross entropy: positions labeled ignore_index are
    excluded from the mean (the 85% unmasked positions)."""
    mask = labels != ignore_index
    safe = jnp.where(mask, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(mask, nll, 0.0)
    return nll.sum() / jnp.maximum(mask.sum(), 1)


def mask_tokens(rng, input_ids, vocab_size: int, mask_token: int,
                mask_prob: float = 0.15):
    """Standard BERT masking: pick mask_prob positions as MLM targets
    (80% [MASK] / 10% random / 10% kept); all other labels are -100."""
    import numpy as np
    ids = np.asarray(input_ids)
    labels = np.full_like(ids, -100)
    pick = rng.random_sample(ids.shape) < mask_prob
    labels[pick] = ids[pick]
    action = rng.random_sample(ids.shape)
    ids = ids.copy()
    ids[pick & (action < 0.8)] = mask_token
    rand = pick & (action >= 0.8) & (action < 0.9)
    ids[rand] = rng.randint(0, vocab_size, size=int(rand.sum()))
    return ids, labels


def bert_sharding_rules(fsdp: bool = True) -> ShardingRules:
    """Megatron-style TP + optional FSDP for the encoder: qkv/ffn_in
    column-parallel, out/ffn_out row-parallel, embeddings vocab/ctx
    sharded (same no-trailing-dim-sharding stance as gpt2's rules —
    see gpt2_sharding_rules for the remat rationale)."""
    f = "fsdp" if fsdp else None
    emb_spec = P(("tensor", "fsdp") if fsdp else "tensor", None)
    return ShardingRules([
        (r"wte$", emb_spec),
        (r"wpe$", emb_spec),
        (r"wtt$", P(None, None)),
        (r"mlm_bias$", P(None)),
        (r"(qkv|ffn_in|pooler)/kernel$", P(f, "tensor")),
        # mlm_dense output feeds the TIED decode einsum against wte:
        # tensor-sharding it would hand wte a dim-sharded gradient
        # contribution that conflicts with its vocab-sharded spec
        # (involuntary full remat in the embedding backward). Keep the
        # head fsdp-only; it is a single small [d, d] matmul.
        (r"mlm_dense/kernel$", P(f, None)),
        (r"(attn/out|ffn_out)/kernel$", P("tensor", f)),
        (r"bias$", P(None)),
        (r"(ln_\w+|scale)$", P(None)),
        (r".*", P(None)),
    ])
