"""ResNet family in flax (v1.5 bottleneck, as used by the reference's AIR
image benchmarks — doc/source/ray-air/benchmarks.rst GPU image training).

TPU notes: NHWC layout (XLA-TPU native), bfloat16 conv compute with fp32
batch-norm statistics, channel counts multiples of 128 in the deep stages so
convs tile the MXU cleanly.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)
    num_classes: int = 1000
    width: int = 64
    bottleneck: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    small_inputs: bool = False    # CIFAR-style stem (3x3, no maxpool)


def resnet50(num_classes: int = 1000, **kw) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(3, 4, 6, 3), bottleneck=True,
                        num_classes=num_classes, **kw)


def resnet18(num_classes: int = 1000, **kw) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(2, 2, 2, 2), bottleneck=False,
                        num_classes=num_classes, **kw)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    config: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 name="conv2")(y)
        y = norm(name="bn2")(y)
        y = nn.relu(y)
        y = conv(4 * self.filters, (1, 1), name="conv3")(y)
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(4 * self.filters, (1, 1),
                            (self.strides, self.strides),
                            name="downsample")(residual)
            residual = norm(name="bn_ds")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    config: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), name="conv2")(y)
        y = norm(name="bn2", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides),
                            name="downsample")(residual)
            residual = norm(name="bn_ds")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(cfg.dtype)
        if cfg.small_inputs:
            x = conv(cfg.width, (3, 3), name="conv_stem")(x)
        else:
            x = conv(cfg.width, (7, 7), (2, 2), name="conv_stem")(x)
        x = norm(name="bn_stem")(x)
        x = nn.relu(x)
        if not cfg.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block_cls = BottleneckBlock if cfg.bottleneck else BasicBlock
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if (i > 0 and j == 0) else 1
                x = block_cls(cfg.width * (2 ** i), strides, cfg,
                              name=f"stage{i}_block{j}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                     param_dtype=cfg.param_dtype, name="head")(x)
        return x
