"""GPT-2 in flax, designed mesh-first.

The reference has no model zoo (its Train wraps user torch models); this model
family exists because the build's north-star benchmarks (BASELINE.md: GPT-2
124M ≥40% MFU on v4) need TPU-optimal reference models. Design choices for
the MXU/HBM (see SURVEY.md §7 and the pallas guide):

- bfloat16 activations/weights by default, fp32 layernorm + logits + loss.
- All matmuls keep a trailing dim that is a multiple of 128 (MXU tiles).
- Attention dispatches to ray_tpu.ops (pallas flash attention on TPU,
  XLA einsum fallback elsewhere, ring attention when the mesh has a
  nontrivial `sequence` axis).
- Sharding is declared as logical-axis rules (gpt2_sharding_rules):
  Megatron-style tensor parallel + optional FSDP on the hidden axis, so the
  same model runs DP, FSDP, TP, SP and combinations by changing the mesh.
- `remat` checkpoints each block to trade FLOPs for HBM.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ray_tpu.mesh.sharding import ShardingRules


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304          # padded to a multiple of 128 (MXU)
    n_ctx: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # Off by default: at 124M/1024ctx activations fit HBM and remat costs
    # ~13% MFU (measured 30.1% -> 26.1% on v5e). Enable for big models.
    remat: bool = False
    attention_impl: str = "auto"     # auto | xla | flash | ring

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


def gpt2_124m(**overrides) -> GPT2Config:
    return GPT2Config(**overrides)


def gpt2_tiny(**overrides) -> GPT2Config:
    """Test-size config for CPU-mesh tests."""
    d = dict(vocab_size=256, n_ctx=64, n_embd=64, n_layer=2, n_head=4)
    d.update(overrides)
    return GPT2Config(**d)


class CausalSelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        B, T, C = x.shape
        qkv = nn.Dense(3 * cfg.n_embd, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, cfg.n_head, cfg.head_dim)

        q, k, v = heads(q), heads(k), heads(v)
        from ray_tpu.ops.attention import multi_head_attention
        y = multi_head_attention(q, k, v, causal=True,
                                 impl=cfg.attention_impl)
        y = y.reshape(B, T, C)
        y = nn.Dense(cfg.n_embd, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_proj")(y)
        if cfg.dropout > 0:
            y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return y


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_fc")(x)
        h = nn.gelu(h)
        h = nn.Dense(cfg.n_embd, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_proj")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        # LayerNorm in fp32 for stability, cast back for the matmuls.
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_1")(x)
        x = x + CausalSelfAttention(cfg, name="attn")(
            h.astype(cfg.dtype), deterministic)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_2")(x)
        x = x + MLP(cfg, name="mlp")(h.astype(cfg.dtype), deterministic)
        return x


class GPT2(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True,
                 return_features: bool = False):
        cfg = self.config
        B, T = input_ids.shape
        wte = self.param(
            "wte", nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        wpe = self.param(
            "wpe", nn.initializers.normal(0.01),
            (cfg.n_ctx, cfg.n_embd), cfg.param_dtype)
        x = wte[input_ids].astype(cfg.dtype) + \
            wpe[None, :T].astype(cfg.dtype)
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=(2,))
        for i in range(cfg.n_layer):
            x = block(cfg, name=f"h_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if return_features:
            # For the fused chunked loss: final hidden states; the tied
            # embedding is fetched from params by the caller.
            return x.astype(cfg.dtype)
        # Tied embeddings. bf16 operands on the MXU with fp32
        # accumulation — fp32 operands would halve matmul throughput for
        # ~30% of the model's FLOPs (vocab is 50k wide).
        logits = jax.lax.dot_general(
            x.astype(cfg.dtype), wte.astype(cfg.dtype),
            (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return logits


def cross_entropy_loss(logits, targets, ignore_index: int = -100):
    """Mean token cross-entropy in fp32."""
    mask = (targets != ignore_index)
    targets = jnp.where(mask, targets, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1)


def linear_cross_entropy(features, wte, targets,
                         ignore_index: int = -100):
    """Tied-embedding projection + cross-entropy via logsumexp-gather.

    Keeps the [B, T, V] logits fp32 (needed for a stable softmax over
    50k classes) but never materializes log-softmax as a saved
    residual — backward recomputes softmax from the logits, so HBM
    sees one logits tensor instead of two. Measured on v5e (GPT-2-124M
    b24, tools/mfu_round2.py): 46.9% MFU vs 42.5% for the
    log_softmax/take_along_axis formulation, and it beats the
    scan-chunked variant (fused_linear_cross_entropy) by 7+ points —
    XLA overlaps the one big projection better than a serialized scan.
    """
    mask = (targets != ignore_index)
    tgt = jnp.where(mask, targets, 0)
    logits = jax.lax.dot_general(
        features, wte.astype(features.dtype), (((2,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
    return ((lse - gold) * mask).sum() / jnp.maximum(mask.sum(), 1)


def fused_linear_cross_entropy(features, wte, targets,
                               chunk: int = 128,
                               ignore_index: int = -100):
    """Projection + softmax-xent over sequence chunks: never
    materializes the [B, T, vocab] fp32 logits (6 GiB at B=32/T=1024 —
    the single biggest HBM allocation of the naive path). Each scan
    step is rematerialized, so the backward recomputes one chunk's
    logits at a time instead of saving them all.

    features: [B, T, C] (bf16), wte: [V, C], targets: [B, T] int.
    """
    B, T, C = features.shape
    n_chunks = max(1, T // chunk)
    assert T % n_chunks == 0, f"seq {T} not divisible by chunk {chunk}"
    step = T // n_chunks
    xs = features.reshape(B, n_chunks, step, C).swapaxes(0, 1)
    ts = targets.reshape(B, n_chunks, step).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_loss(xx, tt):
        logits = jax.lax.dot_general(
            xx, wte.astype(xx.dtype), (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        mask = (tt != ignore_index)
        tt = jnp.where(mask, tt, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, tt[..., None], axis=-1)[..., 0]
        return -(ll * mask).sum(), mask.sum()

    def body(carry, inp):
        loss_sum, count = carry
        ls, cnt = chunk_loss(*inp)
        return (loss_sum + ls, count + cnt), None

    (loss_sum, count), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), (xs, ts))
    return loss_sum / jnp.maximum(count, 1)


def gpt2_sharding_rules(fsdp: bool = True) -> ShardingRules:
    """Megatron-style TP + optional FSDP rules for flax GPT-2 params.

    Param paths look like: params/h_0/attn/c_attn/kernel.
    Column-parallel (output sharded on `tensor`): c_attn, c_fc.
    Row-parallel (input sharded on `tensor`): attn c_proj, mlp c_proj.
    Embeddings shard vocab/ctx over `tensor`; FSDP shards the remaining
    large dim over `fsdp`.
    """
    f = "fsdp" if fsdp else None
    # Embeddings are vocab/ctx-parallel with the embedding dim UNSHARDED:
    # sharding wte/wpe's trailing dim over `fsdp` forces the partitioner
    # to reshard batch-sharded (data, fsdp) activation gradients onto an
    # embedding-dim fsdp layout with a transposed mesh order — an
    # "involuntary full rematerialization" (replicate-then-reshard) in
    # the embedding backward on dp x fsdp x tp meshes. Sharding only the
    # vocab/ctx dim (over tensor AND fsdp) keeps dwte/dwpe a pure
    # scatter into row shards; the dryrun log is remat-warning-free.
    wte_spec = P(("tensor", "fsdp") if fsdp else "tensor", None)
    return ShardingRules([
        (r"attn/c_attn/kernel", P(f, "tensor")),
        (r"attn/c_proj/kernel", P("tensor", f)),
        (r"mlp/c_fc/kernel",    P(f, "tensor")),
        (r"mlp/c_proj/kernel",  P("tensor", f)),
        (r"attn/c_attn/bias",   P("tensor")),
        (r"mlp/c_fc/bias",      P("tensor")),
        (r"wte$",               wte_spec),
        (r"wpe$",               P(f, None)),
        # ln_*/scale|bias and remaining biases: replicate (default).
    ])


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: GPT2Config, seq_len: Optional[int] = None) -> float:
    """Approximate training FLOPs/token (fwd+bwd ≈ 6N + attention)."""
    T = seq_len or cfg.n_ctx
    n_params = (cfg.vocab_size * cfg.n_embd + cfg.n_ctx * cfg.n_embd +
                cfg.n_layer * (12 * cfg.n_embd ** 2) +
                2 * cfg.n_embd)
    # 6 flops/param/token for fwd+bwd matmuls + attention term.
    attn = 12 * cfg.n_layer * cfg.n_embd * T
    return 6.0 * n_params + attn
