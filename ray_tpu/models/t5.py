"""T5: encoder-decoder transformer with relative position biases.

Completes the model-family triangle (decoder: GPT-2/Llama/Mixtral,
encoder: BERT, encoder-decoder: here) for the seq2seq shape of
translation/summarization fleets. Faithful T5 ingredients — shared
embedding, bucketed relative-position attention bias (no absolute
positions), RMSNorm-style pre-norm, tied LM head — with the repo's
TPU conventions: fp32 norms around cfg.dtype matmuls, attention via
ops.attention (biases carry both the rel-pos term and padding masks),
sharding as logical-axis rules, greedy decode as a jitted lax.scan.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.mesh.sharding import ShardingRules


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    dim: int = 512
    n_heads: int = 8
    n_enc_layers: int = 6
    n_dec_layers: int = 6
    hidden_dim: int = 2048
    rel_pos_buckets: int = 32
    rel_pos_max_distance: int = 128
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def t5_small(**overrides) -> T5Config:
    return T5Config(**overrides)


def t5_tiny(**overrides) -> T5Config:
    d = dict(vocab_size=512, dim=64, n_heads=4, n_enc_layers=2,
             n_dec_layers=2, hidden_dim=128, rel_pos_buckets=8,
             rel_pos_max_distance=32, dtype=jnp.float32)
    d.update(overrides)
    return T5Config(**d)


def relative_position_bucket(relative_position, bidirectional: bool,
                             num_buckets: int, max_distance: int):
    """T5's bucketing: half the buckets for exact small offsets, the
    rest logarithmically out to max_distance (Raffel et al. 2020)."""
    rp = relative_position
    bucket = 0
    if bidirectional:
        num_buckets //= 2
        bucket += (rp > 0).astype(jnp.int32) * num_buckets
        rp = jnp.abs(rp)
    else:
        rp = -jnp.minimum(rp, 0)
    max_exact = num_buckets // 2
    is_small = rp < max_exact
    log_big = max_exact + (
        jnp.log(rp.astype(jnp.float32) / max_exact + 1e-6) /
        jnp.log(max_distance / max_exact) *
        (num_buckets - max_exact)).astype(jnp.int32)
    log_big = jnp.minimum(log_big, num_buckets - 1)
    return bucket + jnp.where(is_small, rp, log_big)


class RelPosBias(nn.Module):
    """Per-head additive attention bias from bucketed relative
    positions; shared across layers of one stack (T5 shares the first
    layer's table — here one table per stack, same capability)."""

    config: T5Config
    bidirectional: bool

    @nn.compact
    def __call__(self, q_len: int, k_len: int):
        cfg = self.config
        table = self.param(
            "rel_bias", nn.initializers.normal(0.02),
            (cfg.rel_pos_buckets, cfg.n_heads), jnp.float32)
        ctx = jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        buckets = relative_position_bucket(
            mem - ctx, self.bidirectional, cfg.rel_pos_buckets,
            cfg.rel_pos_max_distance)
        bias = table[buckets]                    # [Tq, Tk, H]
        return jnp.transpose(bias, (2, 0, 1))[None]   # [1, H, Tq, Tk]


class T5Attention(nn.Module):
    config: T5Config
    causal: bool = False

    @nn.compact
    def __call__(self, x, kv=None, bias=None):
        cfg = self.config
        kv = x if kv is None else kv
        B, Tq, _ = x.shape
        Tk = kv.shape[1]
        q = nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="q")(x)
        k = nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="k")(kv)
        v = nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="v")(kv)

        def heads(t, T):
            return t.reshape(B, T, cfg.n_heads, cfg.head_dim)

        from ray_tpu.ops.attention import multi_head_attention
        y = multi_head_attention(heads(q, Tq), heads(k, Tk),
                                 heads(v, Tk), causal=self.causal,
                                 impl="xla", bias=bias)
        y = y.reshape(B, Tq, cfg.dim)
        return nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="o")(y)


class T5FFN(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = nn.Dense(cfg.hidden_dim, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="wi")(x)
        h = nn.relu(h)
        return nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="wo")(h)


from ray_tpu.models.llama import RMSNorm as _LlamaRMSNorm


def RMSNorm(name):
    """Llama's RMSNorm (identical math; dim inferred from input) with
    T5's 1e-6 epsilon."""
    return _LlamaRMSNorm(eps=1e-6, name=name)


class EncoderLayer(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, bias, deterministic: bool = True):
        cfg = self.config

        def drop(v):
            if cfg.dropout > 0:
                return nn.Dropout(cfg.dropout)(v, deterministic)
            return v

        h = RMSNorm(name="ln_attn")(x)
        x = x + drop(T5Attention(cfg, name="attn")(
            h.astype(cfg.dtype), bias=bias))
        h = RMSNorm(name="ln_ffn")(x)
        return x + drop(T5FFN(cfg, name="ffn")(h.astype(cfg.dtype)))


class DecoderLayer(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, enc, self_bias, cross_bias,
                 deterministic: bool = True):
        cfg = self.config

        def drop(v):
            if cfg.dropout > 0:
                return nn.Dropout(cfg.dropout)(v, deterministic)
            return v

        h = RMSNorm(name="ln_self")(x)
        x = x + drop(T5Attention(cfg, causal=True,
                                 name="self_attn")(
            h.astype(cfg.dtype), bias=self_bias))
        h = RMSNorm(name="ln_cross")(x)
        x = x + drop(T5Attention(cfg, name="cross_attn")(
            h.astype(cfg.dtype), kv=enc, bias=cross_bias))
        h = RMSNorm(name="ln_ffn")(x)
        return x + drop(T5FFN(cfg, name="ffn")(h.astype(cfg.dtype)))


from ray_tpu.ops.attention import padding_bias as _pad_bias


class T5(nn.Module):
    """__call__(enc_ids, dec_ids, enc_mask=None) -> [B, Td, vocab]
    logits (teacher forcing; dec_ids are the shifted targets).
    Pass enc_out= to reuse a precomputed encoder state (greedy_decode
    encodes ONCE and scans only the decoder); encode_only=True
    returns just that state."""

    config: T5Config

    @nn.compact
    def __call__(self, enc_ids, dec_ids, enc_mask=None,
                 deterministic: bool = True, encode_only: bool = False,
                 enc_out=None):
        cfg = self.config
        emb = self.param("shared_emb", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        Te, Td = enc_ids.shape[1], dec_ids.shape[1]
        # --- encoder ---
        if enc_out is None:
            x = emb[enc_ids].astype(cfg.dtype)
            enc_bias = RelPosBias(cfg, bidirectional=True,
                                  name="enc_relpos")(Te, Te)
            if enc_mask is not None:
                enc_bias = enc_bias + _pad_bias(enc_mask)
            for i in range(cfg.n_enc_layers):
                x = EncoderLayer(cfg, name=f"enc_{i}")(
                    x, enc_bias, deterministic)
            enc_out = RMSNorm(name="enc_final_ln")(x)
        if encode_only:
            return enc_out
        # --- decoder ---
        y = emb[dec_ids].astype(cfg.dtype)
        # Causality rides the attention op (causal=True on
        # self_attn); only the rel-pos term travels as a bias.
        self_bias = RelPosBias(cfg, bidirectional=False,
                               name="dec_relpos")(Td, Td)
        cross_bias = None
        if enc_mask is not None:
            cross_bias = _pad_bias(enc_mask)
        for i in range(cfg.n_dec_layers):
            y = DecoderLayer(cfg, name=f"dec_{i}")(
                y, enc_out.astype(cfg.dtype), self_bias, cross_bias,
                deterministic)
        y = RMSNorm(name="dec_final_ln")(y)
        # Tied head, T5's 1/sqrt(d) output scaling.
        logits = jnp.einsum("btd,vd->btv", y.astype(cfg.dtype),
                            emb.astype(cfg.dtype))
        return logits * (cfg.dim ** -0.5)


def seq2seq_loss(logits, targets, pad_id: int = 0):
    """Token CE over non-pad target positions."""
    mask = targets != pad_id
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    nll = -jnp.take_along_axis(
        logp, jnp.where(mask, targets, 0)[..., None], -1)[..., 0]
    return jnp.where(mask, nll, 0.0).sum() / \
        jnp.maximum(mask.sum(), 1)


_DECODE_CACHE: dict = {}


def greedy_decode(model: T5, params, enc_ids, max_len: int,
                  bos_id: int = 1, enc_mask=None):
    """Jitted greedy seq2seq decode: the encoder runs ONCE, then one
    lax.scan over target positions re-runs the (short-sequence)
    decoder per step — the classic simple schedule; KV-cached decode
    rides the Llama engine for the decoder-only families. Compiled
    programs cache per (config, shapes) like llama's generate."""
    B = enc_ids.shape[0]
    key = (model.config, B, enc_ids.shape[1], max_len, bos_id,
           enc_mask is not None)
    cached = _DECODE_CACHE.get(key)
    if cached is not None:
        return cached(params, jnp.asarray(enc_ids),
                      None if enc_mask is None else
                      jnp.asarray(enc_mask))

    @jax.jit
    def run(params, enc_ids, enc_mask):
        # Encode ONCE; the scan re-runs only the (short) decoder.
        enc_out = model.apply(params, enc_ids,
                              jnp.zeros((B, 1), jnp.int32),
                              enc_mask=enc_mask, encode_only=True)

        def step(dec_ids, t):
            logits = model.apply(params, enc_ids, dec_ids,
                                 enc_mask=enc_mask, enc_out=enc_out)
            nxt = jnp.argmax(logits[:, t], -1)
            dec_ids = jax.lax.dynamic_update_index_in_dim(
                dec_ids, nxt.astype(jnp.int32), t + 1, axis=1)
            return dec_ids, nxt

        dec0 = jnp.full((B, max_len + 1), 0, jnp.int32)
        dec0 = dec0.at[:, 0].set(bos_id)
        dec, outs = jax.lax.scan(step, dec0, jnp.arange(max_len))
        return dec[:, 1:]

    if len(_DECODE_CACHE) > 16:
        _DECODE_CACHE.clear()     # bound retained executables
    _DECODE_CACHE[key] = run
    return run(params, jnp.asarray(enc_ids),
               None if enc_mask is None else jnp.asarray(enc_mask))


def t5_sharding_rules(fsdp: bool = True) -> ShardingRules:
    """Megatron TP for the stacks, vocab-parallel shared embedding:
    q/k/v/wi column-parallel, o/wo row-parallel over `tensor`; the
    shared embedding's vocab dim shards over (tensor, fsdp).

    Deliberately NO fsdp dim on the stack kernels: double-sharding
    them P(fsdp, tensor) in this THREE-consumer-embedding seq2seq
    graph (enc lookup + dec lookup + tied head) trips an XLA:CPU
    collective-schedule bug — in-process rendezvous deadlocks and,
    when it completes, wrong gradients (fixed-batch loss plateaus at
    ~0.9 where 0.005 is reached with these rules; see round-5 notes).
    The embedding IS the dominant parameter at T5 scale, so fsdp
    still covers the big memory term; revisit kernel fsdp on real
    TPU hardware."""
    emb_spec = P(("tensor", "fsdp") if fsdp else "tensor", None)
    return ShardingRules([
        (r"shared_emb$", emb_spec),
        (r"rel_bias$", P(None, None)),
        (r"(q|k|v|wi)/kernel$", P(None, "tensor")),
        (r"(o|wo)/kernel$", P("tensor", None)),
        (r"scale$", P(None)),
        # remaining params replicate via ShardingRules' implicit
        # default
    ])
