"""State API: cluster introspection.

Capability parity with the reference's state API
(python/ray/experimental/state/api.py list_actors:719/list_tasks:942,
dashboard/state_aggregator.py): filterable listings of actors, tasks,
objects, workers and resource summaries, backed by whichever runtime is
active (local or distributed).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private.worker import global_worker

Filter = Tuple[str, str, Any]   # (key, "="|"!=", value)


def _apply_filters(rows: List[Dict[str, Any]],
                   filters: Optional[List[Filter]]) -> List[Dict]:
    if not filters:
        return rows
    out = []
    for row in rows:
        keep = True
        for key, op, value in filters:
            actual = row.get(key)
            if op == "=":
                keep = actual == value
            elif op == "!=":
                keep = actual != value
            else:
                raise ValueError(f"Unsupported filter op {op!r}")
            if not keep:
                break
        if keep:
            out.append(row)
    return out


def list_actors(filters: Optional[List[Filter]] = None,
                limit: int = 1000) -> List[Dict[str, Any]]:
    return _apply_filters(
        global_worker().runtime.list_actors(), filters)[:limit]


def list_tasks(filters: Optional[List[Filter]] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    return _apply_filters(
        global_worker().runtime.list_tasks(), filters)[:limit]


def list_objects(filters: Optional[List[Filter]] = None,
                 limit: int = 1000) -> List[Dict[str, Any]]:
    return _apply_filters(
        global_worker().runtime.list_objects(), filters)[:limit]


def list_workers() -> List[Dict[str, Any]]:
    rt = global_worker().runtime
    if hasattr(rt, "list_workers"):
        return rt.list_workers()
    return [{"worker_id": "driver", "alive": True,
             "resources": rt.cluster_resources(),
             "available": rt.available_resources()}]


def list_nodes() -> List[Dict[str, Any]]:
    """Per-node membership + hardware snapshots (reporter_agent.py
    role: psutil/TPU stats ride node heartbeats into the head)."""
    rt = global_worker().runtime
    if hasattr(rt, "list_nodes"):
        return rt.list_nodes()
    # local runtime: one in-process "node", sampled directly
    from ray_tpu._private.hw_report import collect_hw_stats
    return [{"node_id": "local", "alive": True,
             "hw": collect_hw_stats()}]


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for t in list_tasks():
        counts[t["state"]] = counts.get(t["state"], 0) + 1
    return counts


def summarize_actors() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for a in list_actors():
        counts[a["state"]] = counts.get(a["state"], 0) + 1
    return counts


def cluster_summary() -> Dict[str, Any]:
    rt = global_worker().runtime
    return {
        "resources_total": rt.cluster_resources(),
        "resources_available": rt.available_resources(),
        "tasks": summarize_tasks(),
        "actors": summarize_actors(),
        # alive only: the headline number must agree with the state
        # rows (dead workers stay listed with alive=False)
        "workers": sum(1 for w in list_workers()
                       if w.get("alive", True)),
    }
