"""Autoscaler SDK (reference: ray.autoscaler.sdk.request_resources).

request_resources pins a standing demand floor: the autoscaler keeps
enough nodes to satisfy these bundles even with an empty task queue
(pre-scaling for anticipated load); calling again replaces the floor,
and request_resources([]) clears it.
"""
from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None
                      ) -> None:
    from ray_tpu._private.worker import global_worker
    out: List[Dict[str, float]] = []
    if num_cpus:
        out.extend({"CPU": 1.0} for _ in range(int(num_cpus)))
    if bundles:
        out.extend(dict(b) for b in bundles)
    rt = global_worker().runtime
    head = getattr(rt, "head", None)
    if head is None:
        raise RuntimeError(
            "request_resources needs the multiprocess runtime "
            "(an autoscaler-managed cluster)")
    head.call("request_resources", out)
