"""StandardAutoscaler: scale the fleet to match demand.

Capability parity with the reference's StandardAutoscaler
(python/ray/autoscaler/_private/autoscaler.py:154,345): each ``update()``
enforces per-type min_workers, launches nodes for unmet pending demands
(bounded by max_workers and upscaling_speed), and terminates nodes idle
longer than idle_timeout_s. TPU node types scale by whole slices.
"""
from __future__ import annotations

import logging
import math
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import (NodeProvider, TAG_NODE_TYPE)
from ray_tpu.autoscaler.resource_demand_scheduler import (
    NodeTypeConfig, get_infeasible_demands, get_nodes_to_launch)

logger = logging.getLogger(__name__)


class StandardAutoscaler:
    def __init__(self, config: Dict, provider: NodeProvider,
                 load_metrics: Optional[LoadMetrics] = None):
        self.provider = provider
        self.load_metrics = load_metrics or LoadMetrics()
        self.update_config(config)
        # node_id -> worker_id binding filled in by the monitor for
        # providers that know it (FakeMultiNodeProvider).
        self.num_launches = 0
        self.num_terminations = 0
        self.infeasible_demands: List[Dict[str, float]] = []

    def update_config(self, config: Dict) -> None:
        self.config = dict(config)
        self.max_workers = config.get("max_workers", 8)
        self.idle_timeout_s = config.get("idle_timeout_s", 60.0)
        self.upscaling_speed = max(
            float(config.get("upscaling_speed", 1.0)), 0.0)
        self.node_types: Dict[str, NodeTypeConfig] = {
            name: NodeTypeConfig.from_config(name, cfg)
            for name, cfg in config.get(
                "available_node_types", {}).items()}

    # -- helpers -------------------------------------------------------------

    def _counts_by_type(self, nodes: List[str]) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for nid in nodes:
            ntype = self.provider.node_tags(nid).get(TAG_NODE_TYPE, "?")
            counts[ntype] = counts.get(ntype, 0) + 1
        return counts

    def _launch(self, ntype: str, count: int) -> None:
        cfg = self.node_types[ntype]
        self.provider.create_node(ntype, cfg.resources, count)
        self.num_launches += count
        logger.info("Autoscaler: launched %d x %s", count, ntype)

    # -- the reconcile step --------------------------------------------------

    def update(self, node_to_worker: Optional[Dict[str, str]] = None
               ) -> None:
        """One reconcile round. ``node_to_worker`` maps provider node ids
        to runtime worker ids (for idle/busy attribution)."""
        node_to_worker = node_to_worker or {}
        nodes = self.provider.non_terminated_nodes()
        counts = self._counts_by_type(nodes)

        # 1. Enforce min_workers per type.
        for cfg in self.node_types.values():
            short = cfg.min_workers - counts.get(cfg.name, 0)
            if short > 0:
                self._launch(cfg.name, short)
                counts[cfg.name] = counts.get(cfg.name, 0) + short

        # 2. Launch for unmet pending demands. Nodes we launched that
        # haven't registered a runtime worker yet count as in-flight
        # capacity so a startup-lag window doesn't multiply launches.
        lm = self.load_metrics
        node_available = [n.available for n in lm.nodes.values()]
        registered = set(lm.nodes)
        pending_launches: Dict[str, int] = {}
        for nid in nodes:
            wid = node_to_worker.get(nid)
            if wid is None and hasattr(self.provider, "worker_id_of"):
                wid = self.provider.worker_id_of(nid)
            if wid is not None and wid in registered:
                continue
            ntype = self.provider.node_tags(nid).get(TAG_NODE_TYPE, "?")
            pending_launches[ntype] = pending_launches.get(ntype, 0) + 1
        # In-flight nodes are already inside `counts`; the scheduler
        # adds their full capacity as free space and counts them toward
        # max_workers, so drop them from the existing tally.
        counts_registered = dict(counts)
        for ntype, cnt in pending_launches.items():
            counts_registered[ntype] = \
                max(0, counts_registered.get(ntype, 0) - cnt)
        to_launch = get_nodes_to_launch(
            self.node_types, counts_registered, node_available,
            lm.pending_demands, self.max_workers,
            pending_launches=pending_launches)
        infeasible = get_infeasible_demands(
            self.node_types, lm.pending_demands)
        if infeasible and infeasible != self.infeasible_demands:
            logger.warning("Autoscaler: infeasible demands %s",
                           infeasible)
        self.infeasible_demands = infeasible
        # upscaling_speed bounds launches per round to
        # ceil(speed * max(current, 1)) per type, like the reference.
        for ntype, cnt in to_launch.items():
            cap = int(math.ceil(
                self.upscaling_speed * max(counts.get(ntype, 0), 1)))
            self._launch(ntype, min(cnt, max(cap, 1)))

        # 3. Terminate idle nodes beyond min_workers.
        nodes = self.provider.non_terminated_nodes()
        counts = self._counts_by_type(nodes)
        for nid in nodes:
            ntype = self.provider.node_tags(nid).get(TAG_NODE_TYPE, "?")
            cfg = self.node_types.get(ntype)
            if cfg and counts.get(ntype, 0) <= cfg.min_workers:
                continue
            wid = node_to_worker.get(nid)
            if wid is None and hasattr(self.provider, "worker_id_of"):
                wid = self.provider.worker_id_of(nid)
            if wid is None or wid not in lm.nodes:
                continue   # not yet registered: treat as starting up
            if lm.nodes[wid].busy:
                continue
            if lm.idle_seconds(wid) >= self.idle_timeout_s:
                self.provider.terminate_node(nid)
                self.num_terminations += 1
                counts[ntype] = counts.get(ntype, 0) - 1
                logger.info("Autoscaler: terminated idle node %s", nid)

    def summary(self) -> Dict:
        nodes = self.provider.non_terminated_nodes()
        return {
            "nodes_by_type": self._counts_by_type(nodes),
            "num_launches": self.num_launches,
            "num_terminations": self.num_terminations,
            "infeasible_demands": list(self.infeasible_demands),
            "load": self.load_metrics.summary(),
        }
