"""Bin-packing resource demand scheduler.

Capability parity with the reference's ResourceDemandScheduler
(python/ray/autoscaler/_private/resource_demand_scheduler.py:46,141):
given pending resource demands and the current node fleet, decide which
node types to launch. TPU-first: node types whose resources include
``TPU`` represent whole ICI slices, so the packing naturally scales by
slices rather than individual chips.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class NodeTypeConfig:
    def __init__(self, name: str, resources: Dict[str, float],
                 min_workers: int = 0, max_workers: int = 2**31):
        self.name = name
        self.resources = dict(resources)
        self.min_workers = min_workers
        self.max_workers = max_workers

    @classmethod
    def from_config(cls, name: str, cfg: Dict) -> "NodeTypeConfig":
        return cls(name, cfg.get("resources", {}),
                   cfg.get("min_workers", 0),
                   cfg.get("max_workers", 2**31))


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _subtract(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


def get_nodes_to_launch(
    node_types: Dict[str, NodeTypeConfig],
    existing_counts: Dict[str, int],
    node_available: List[Dict[str, float]],
    pending_demands: List[Dict[str, float]],
    max_workers: int,
    pending_launches: Optional[Dict[str, int]] = None,
) -> Dict[str, int]:
    """First-fit-decreasing packing of demands onto free space, then onto
    planned launches, then onto new nodes (smallest feasible type).

    Returns {node_type: count} to launch. ``node_available`` is the free
    resources of each live node; ``pending_launches`` are launches already
    in flight (their full capacity counts as free space).
    """
    pending_launches = dict(pending_launches or {})
    total_nodes = sum(existing_counts.values()) + \
        sum(pending_launches.values())
    # Free space: live nodes' available + in-flight launches' capacity.
    space: List[Dict[str, float]] = [dict(a) for a in node_available]
    for ntype, cnt in pending_launches.items():
        cfg = node_types.get(ntype)
        if cfg:
            space.extend(dict(cfg.resources) for _ in range(cnt))

    to_launch: Dict[str, int] = {}
    demands = sorted(pending_demands,
                     key=lambda d: (-len(d), -sum(d.values())))
    for demand in demands:
        if not demand:
            continue
        placed = False
        for avail in space:
            if _fits(avail, demand):
                _subtract(avail, demand)
                placed = True
                break
        if placed:
            continue
        if total_nodes >= max_workers:
            continue
        # Pick the smallest feasible node type (fewest total resources
        # that still fit the demand), respecting per-type max_workers.
        best: Optional[NodeTypeConfig] = None
        for cfg in node_types.values():
            launched = existing_counts.get(cfg.name, 0) + \
                pending_launches.get(cfg.name, 0) + \
                to_launch.get(cfg.name, 0)
            if launched >= cfg.max_workers:
                continue
            if not _fits(cfg.resources, demand):
                continue
            if best is None or \
                    sum(cfg.resources.values()) < \
                    sum(best.resources.values()):
                best = cfg
        if best is None:
            continue   # infeasible demand: report, never launch
        to_launch[best.name] = to_launch.get(best.name, 0) + 1
        total_nodes += 1
        avail = dict(best.resources)
        _subtract(avail, demand)
        space.append(avail)
    return to_launch


def get_infeasible_demands(
    node_types: Dict[str, NodeTypeConfig],
    pending_demands: List[Dict[str, float]],
) -> List[Dict[str, float]]:
    """Demands no configured node type could ever satisfy."""
    out = []
    for demand in pending_demands:
        if demand and not any(_fits(cfg.resources, demand)
                              for cfg in node_types.values()):
            out.append(demand)
    return out
