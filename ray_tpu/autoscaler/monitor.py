"""Autoscaler monitor: the polling loop that drives StandardAutoscaler.

Capability parity with the reference's monitor process
(python/ray/autoscaler/_private/monitor.py:125), run here as a thread
against a live HeadService, plus ``AutoscalingCluster`` — the e2e test
vehicle equivalent to ray.cluster_utils.AutoscalingCluster
(python/ray/cluster_utils.py:24) with processes as fake nodes.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import FakeMultiNodeProvider


class Monitor:
    def __init__(self, head_service, autoscaler: StandardAutoscaler,
                 update_interval_s: float = 0.25):
        self._head = head_service
        self._autoscaler = autoscaler
        self._interval = update_interval_s
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autoscaler-monitor")

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stopped.set()
        self._thread.join(timeout=5)

    def _run(self):
        while not self._stopped.is_set():
            try:
                snapshot = self._head.load_metrics_snapshot()
                self._autoscaler.load_metrics.update(snapshot)
                self._autoscaler.update()
            except Exception:
                import traceback
                traceback.print_exc()
            self._stopped.wait(self._interval)


class AutoscalingCluster:
    """A Cluster that starts empty and scales via the autoscaler."""

    def __init__(self, config: Dict,
                 store_capacity: int = 256 * 1024 * 1024,
                 update_interval_s: float = 0.25):
        from ray_tpu.runtime.cluster_utils import Cluster
        self.cluster = Cluster(num_workers=0,
                               store_capacity=store_capacity,
                               connect=False)
        self.provider = FakeMultiNodeProvider(self.cluster.node)
        self.autoscaler = StandardAutoscaler(
            config, self.provider, LoadMetrics())
        self.monitor = Monitor(self.cluster.node.head_service,
                               self.autoscaler,
                               update_interval_s).start()

    @property
    def runtime(self):
        return self.cluster.runtime

    def connect(self):
        return self.cluster.connect()

    def num_nodes(self) -> int:
        return len(self.provider.non_terminated_nodes())

    def wait_for_nodes(self, n: int, timeout: float = 30) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.num_nodes() >= n:
                return True
            time.sleep(0.1)
        return False

    def shutdown(self):
        self.monitor.stop()
        self.cluster.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
