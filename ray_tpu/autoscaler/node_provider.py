"""Pluggable node providers for the autoscaler.

Capability parity with the reference's ``NodeProvider`` interface
(python/ray/autoscaler/node_provider.py:13) and its fake multi-node
backend (python/ray/autoscaler/_private/fake_multi_node/node_provider.py),
re-designed for TPU-first scaling: a "node" is a whole host — for TPU
node types, a whole ICI slice — so scaling granularity is slice-granular
by construction (SURVEY.md §7 step 9).
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

TAG_NODE_TYPE = "node-type"
TAG_NODE_STATUS = "node-status"
STATUS_UP = "up-to-date"
STATUS_PENDING = "pending"


class NodeProvider:
    """Abstract cloud/cluster backend.

    Implementations manage opaque ``node_id`` strings. All methods are
    called from the autoscaler's single update thread.
    """

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def create_node(self, node_type: str, resources: Dict[str, float],
                    count: int = 1) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        return node_id in self.non_terminated_nodes()

    def internal_ip(self, node_id: str) -> Optional[str]:
        return None


class MockProvider(NodeProvider):
    """In-memory provider for pure-unit autoscaler tests (reference:
    python/ray/tests/autoscaler_test_utils.py MockProvider)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.nodes: Dict[str, Dict[str, Any]] = {}
        self.num_creates = 0
        self.num_terminates = 0

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return [nid for nid, n in self.nodes.items()
                    if not n["terminated"]]

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self.nodes[node_id]["tags"])

    def create_node(self, node_type: str, resources: Dict[str, float],
                    count: int = 1) -> List[str]:
        created = []
        with self._lock:
            for _ in range(count):
                nid = f"node-{uuid.uuid4().hex[:8]}"
                self.nodes[nid] = {
                    "tags": {TAG_NODE_TYPE: node_type,
                             TAG_NODE_STATUS: STATUS_UP},
                    "resources": dict(resources),
                    "terminated": False,
                    "created_at": time.time(),
                }
                self.num_creates += 1
                created.append(nid)
        return created

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            if node_id in self.nodes:
                self.nodes[node_id]["terminated"] = True
                self.num_terminates += 1


class FakeMultiNodeProvider(NodeProvider):
    """Provider backed by real worker *processes* of a running
    :class:`ray_tpu.runtime.node.NodeManager` — the analogue of the
    reference's fake_multi_node provider that lets autoscaler e2e tests
    run with processes as fake nodes (SURVEY.md §4.2)."""

    def __init__(self, node_manager):
        self._nm = node_manager
        self._lock = threading.Lock()
        # provider node_id -> (worker_id, node_type)
        self._nodes: Dict[str, Dict[str, Any]] = {}

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            out = []
            for nid, rec in self._nodes.items():
                proc = self._nm.procs.get(rec["worker_id"])
                if proc is not None and proc.poll() is None:
                    out.append(nid)
            return out

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            rec = self._nodes[node_id]
            return {TAG_NODE_TYPE: rec["node_type"],
                    TAG_NODE_STATUS: STATUS_UP}

    def worker_id_of(self, node_id: str) -> Optional[str]:
        with self._lock:
            rec = self._nodes.get(node_id)
            return rec["worker_id"] if rec else None

    def node_id_of_worker(self, worker_id: str) -> Optional[str]:
        with self._lock:
            for nid, rec in self._nodes.items():
                if rec["worker_id"] == worker_id:
                    return nid
            return None

    def create_node(self, node_type: str, resources: Dict[str, float],
                    count: int = 1) -> List[str]:
        created = []
        for _ in range(count):
            index = len(self._nm.procs)
            worker_id = self._nm.start_worker(index, dict(resources))
            nid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
            with self._lock:
                self._nodes[nid] = {"worker_id": worker_id,
                                    "node_type": node_type}
            created.append(nid)
        return created

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            rec = self._nodes.pop(node_id, None)
        if rec is not None:
            self._nm.kill_worker(rec["worker_id"])
