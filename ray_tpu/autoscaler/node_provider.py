"""Pluggable node providers for the autoscaler.

Capability parity with the reference's ``NodeProvider`` interface
(python/ray/autoscaler/node_provider.py:13) and its fake multi-node
backend (python/ray/autoscaler/_private/fake_multi_node/node_provider.py),
re-designed for TPU-first scaling: a "node" is a whole host — for TPU
node types, a whole ICI slice — so scaling granularity is slice-granular
by construction (SURVEY.md §7 step 9).
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

TAG_NODE_TYPE = "node-type"
TAG_NODE_STATUS = "node-status"
STATUS_UP = "up-to-date"
STATUS_PENDING = "pending"


class NodeProvider:
    """Abstract cloud/cluster backend.

    Implementations manage opaque ``node_id`` strings. All methods are
    called from the autoscaler's single update thread.
    """

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def create_node(self, node_type: str, resources: Dict[str, float],
                    count: int = 1) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        return node_id in self.non_terminated_nodes()

    def internal_ip(self, node_id: str) -> Optional[str]:
        return None


class MockProvider(NodeProvider):
    """In-memory provider for pure-unit autoscaler tests (reference:
    python/ray/tests/autoscaler_test_utils.py MockProvider)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.nodes: Dict[str, Dict[str, Any]] = {}
        self.num_creates = 0
        self.num_terminates = 0

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return [nid for nid, n in self.nodes.items()
                    if not n["terminated"]]

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self.nodes[node_id]["tags"])

    def create_node(self, node_type: str, resources: Dict[str, float],
                    count: int = 1) -> List[str]:
        created = []
        with self._lock:
            for _ in range(count):
                nid = f"node-{uuid.uuid4().hex[:8]}"
                self.nodes[nid] = {
                    "tags": {TAG_NODE_TYPE: node_type,
                             TAG_NODE_STATUS: STATUS_UP},
                    "resources": dict(resources),
                    "terminated": False,
                    "created_at": time.time(),
                }
                self.num_creates += 1
                created.append(nid)
        return created

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            if node_id in self.nodes:
                self.nodes[node_id]["terminated"] = True
                self.num_terminates += 1


class FakeMultiNodeProvider(NodeProvider):
    """Provider backed by real worker *processes* of a running
    :class:`ray_tpu.runtime.node.NodeManager` — the analogue of the
    reference's fake_multi_node provider that lets autoscaler e2e tests
    run with processes as fake nodes (SURVEY.md §4.2)."""

    def __init__(self, node_manager):
        self._nm = node_manager
        self._lock = threading.Lock()
        # provider node_id -> (worker_id, node_type)
        self._nodes: Dict[str, Dict[str, Any]] = {}

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            out = []
            for nid, rec in self._nodes.items():
                proc = self._nm.procs.get(rec["worker_id"])
                if proc is not None and proc.poll() is None:
                    out.append(nid)
            return out

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            rec = self._nodes[node_id]
            return {TAG_NODE_TYPE: rec["node_type"],
                    TAG_NODE_STATUS: STATUS_UP}

    def worker_id_of(self, node_id: str) -> Optional[str]:
        with self._lock:
            rec = self._nodes.get(node_id)
            return rec["worker_id"] if rec else None

    def node_id_of_worker(self, worker_id: str) -> Optional[str]:
        with self._lock:
            for nid, rec in self._nodes.items():
                if rec["worker_id"] == worker_id:
                    return nid
            return None

    def create_node(self, node_type: str, resources: Dict[str, float],
                    count: int = 1) -> List[str]:
        created = []
        for _ in range(count):
            index = len(self._nm.procs)
            worker_id = self._nm.start_worker(index, dict(resources))
            nid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
            with self._lock:
                self._nodes[nid] = {"worker_id": worker_id,
                                    "node_type": node_type}
            created.append(nid)
        return created

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            rec = self._nodes.pop(node_id, None)
        if rec is not None:
            self._nm.kill_worker(rec["worker_id"])


# ---------------------------------------------------------------------------
# TPU pod/slice provider
# ---------------------------------------------------------------------------

# accelerator type -> (hosts per slice, chips per host). Slice topology
# table for the TPU generations this framework targets; a slice is the
# atomic provisioning unit (you cannot get half an ICI domain).
TPU_TOPOLOGIES: Dict[str, Any] = {
    "v4-8":    (1, 4),
    "v4-16":   (2, 4),
    "v4-32":   (4, 4),
    "v5e-1":   (1, 1),
    "v5e-4":   (1, 4),
    "v5e-8":   (2, 4),
    "v5e-16":  (4, 4),
    "v5e-32":  (8, 4),
    "v5p-8":   (1, 4),
    "v5p-16":  (2, 4),
}

QR_PROVISIONING = "PROVISIONING"
QR_READY = "READY"
QR_DELETING = "DELETING"
QR_PREEMPTING = "PREEMPTING"


class SimulatedTPUCloud:
    """Simulated queued-resource backend with the request/response
    shape of the Cloud TPU API (queued resources: create -> PROVISIONING
    -> READY; delete -> DELETING -> gone). Stands in for the real API
    in this environment; a production backend implements the same four
    methods against the TPU REST surface (SURVEY.md §7 step 9 allows a
    simulated backend as the design artifact).

    ``provision_delay_s`` models slice spin-up; ``capacity`` models
    stockouts per accelerator type (create beyond it parks the queued
    resource in PROVISIONING forever — exactly how real stockouts
    surface).

    Preemption model: ``preempt(name, grace_s, stockout_s)`` moves a
    READY slice to PREEMPTING; the slice keeps serving through the
    grace window (a real notice arrives before the slice dies), then
    vanishes. An optional post-preemption stockout window blocks new
    READY promotions of that accelerator type — preempted capacity is
    usually gone precisely because the region ran out of it."""

    def __init__(self, provision_delay_s: float = 0.0,
                 capacity: Optional[Dict[str, int]] = None):
        self._lock = threading.Lock()
        self._delay = provision_delay_s
        self._capacity = dict(capacity or {})
        self._qrs: Dict[str, Dict[str, Any]] = {}
        self._subnet = 0     # monotonic: deleted slices never reuse IPs
        # accel type -> wall time before which no new slice goes READY
        self._stockout_until: Dict[str, float] = {}
        # Event log of every preemption (tests/harnesses assert on it).
        self.preemptions: List[Dict[str, Any]] = []

    @property
    def provision_delay_s(self) -> float:
        """The modeled slice spin-up time (capacity providers use it
        to compute honest remaining-ETA hints)."""
        return self._delay

    def create_queued_resource(self, name: str, accelerator_type: str
                               ) -> Dict[str, Any]:
        if accelerator_type not in TPU_TOPOLOGIES:
            raise ValueError(
                f"unknown accelerator_type {accelerator_type!r}")
        hosts, chips = TPU_TOPOLOGIES[accelerator_type]
        with self._lock:
            if name in self._qrs:
                raise ValueError(f"queued resource {name!r} exists")
            subnet = self._subnet
            self._subnet += 1
            self._qrs[name] = {
                "name": name,
                "accelerator_type": accelerator_type,
                "state": QR_PROVISIONING,
                "create_time": time.time(),
                "node_ips": [
                    f"10.{128 + subnet // 256}.{subnet % 256}.{h}"
                    for h in range(hosts)],
                "hosts": hosts,
                "chips_per_host": chips,
            }
            return dict(self._qrs[name])

    def _ready_count(self, accel: str) -> int:
        return sum(1 for q in self._qrs.values()
                   if q["accelerator_type"] == accel and
                   q["state"] == QR_READY)

    def describe(self, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            q = self._qrs.get(name)
            if q is None:
                return None
            now = time.time()
            if q["state"] == QR_PREEMPTING and \
                    now >= q["preempt_deadline"]:
                # Grace window over: the slice is gone, exactly as if
                # the cloud reclaimed it out from under the workload.
                self._qrs.pop(name, None)
                return None
            if q["state"] == QR_PROVISIONING and \
                    now - q["create_time"] >= self._delay:
                accel = q["accelerator_type"]
                cap = self._capacity.get(accel)
                stocked_out = now < self._stockout_until.get(accel, 0.0)
                if not stocked_out and (
                        cap is None or self._ready_count(accel) < cap):
                    q["state"] = QR_READY
            return dict(q)

    def preempt(self, name: str, grace_s: float = 0.0,
                stockout_s: float = 0.0) -> Dict[str, Any]:
        """Preempt a slice: READY -> PREEMPTING for ``grace_s`` (the
        advance notice real clouds deliver), then gone. ``stockout_s``
        additionally blocks READY promotion of this accelerator type —
        the capacity squeeze that caused the preemption persists."""
        with self._lock:
            q = self._qrs.get(name)
            if q is None:
                raise ValueError(f"unknown queued resource {name!r}")
            now = time.time()
            q["state"] = QR_PREEMPTING
            q["preempt_deadline"] = now + grace_s
            accel = q["accelerator_type"]
            if stockout_s > 0:
                self._stockout_until[accel] = max(
                    self._stockout_until.get(accel, 0.0),
                    now + stockout_s)
            self.preemptions.append({
                "name": name, "accelerator_type": accel,
                "time": now, "grace_s": grace_s,
                "stockout_s": stockout_s})
            return dict(q)

    def preemption_notice(self, name: str) -> Optional[float]:
        """Seconds of grace remaining for a PREEMPTING slice (what the
        in-VM metadata server exposes on real TPUs); None when no
        notice is active for ``name``."""
        with self._lock:
            q = self._qrs.get(name)
            if q is None or q["state"] != QR_PREEMPTING:
                return None
            return max(0.0, q["preempt_deadline"] - time.time())

    def ready_slice_count(self, accelerator_type: str) -> int:
        """READY slices of one accelerator type — the natural elastic
        capacity oracle for a trainer whose workers each ride one
        slice. Runs expirations first so a lapsed grace window is not
        counted as live capacity."""
        with self._lock:
            names = list(self._qrs)
        for n in names:
            self.describe(n)
        with self._lock:
            return self._ready_count(accelerator_type)

    def delete_queued_resource(self, name: str) -> None:
        with self._lock:
            self._qrs.pop(name, None)

    def list_queued_resources(self) -> List[Dict[str, Any]]:
        with self._lock:
            names = list(self._qrs)
        out = [self.describe(n) for n in names]
        return [q for q in out if q is not None]


class TPUPodProvider(NodeProvider):
    """Slice-granular TPU provider (reference role:
    python/ray/autoscaler/_private/gcp/node_provider.py + tpu.py —
    re-designed TPU-first): one autoscaler "node" IS one ICI slice
    (all its hosts), provisioned and terminated atomically through a
    queued-resource backend. Scaling never splits an ICI domain, so a
    launched node always carries a usable collective mesh.

    ``node_type`` names must be accelerator types from TPU_TOPOLOGIES
    (e.g. "v5e-16"). Use :func:`tpu_node_types` to generate the
    matching ``available_node_types`` autoscaler config."""

    def __init__(self, cloud: Optional[SimulatedTPUCloud] = None,
                 name_prefix: str = "raytpu"):
        self.cloud = cloud or SimulatedTPUCloud()
        self._prefix = name_prefix
        self._lock = threading.Lock()
        self._nodes: Dict[str, str] = {}   # node_id -> accelerator type

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            ids = list(self._nodes)
        return [nid for nid in ids
                if self.cloud.describe(nid) is not None]

    def node_tags(self, node_id: str) -> Dict[str, str]:
        q = self.cloud.describe(node_id)
        with self._lock:
            accel = self._nodes.get(node_id, "?")
        status = STATUS_UP if q and q["state"] == QR_READY \
            else STATUS_PENDING
        return {TAG_NODE_TYPE: accel, TAG_NODE_STATUS: status}

    def create_node(self, node_type: str, resources: Dict[str, float],
                    count: int = 1) -> List[str]:
        created = []
        for _ in range(count):
            nid = f"{self._prefix}-{node_type}-{uuid.uuid4().hex[:6]}"
            self.cloud.create_queued_resource(nid, node_type)
            with self._lock:
                self._nodes[nid] = node_type
            created.append(nid)
        return created

    def terminate_node(self, node_id: str) -> None:
        self.cloud.delete_queued_resource(node_id)
        with self._lock:
            self._nodes.pop(node_id, None)

    def is_running(self, node_id: str) -> bool:
        q = self.cloud.describe(node_id)
        return bool(q and q["state"] == QR_READY)

    def internal_ip(self, node_id: str) -> Optional[str]:
        q = self.cloud.describe(node_id)
        return q["node_ips"][0] if q else None

    def slice_hosts(self, node_id: str) -> List[str]:
        """All host IPs of the slice (the gang bootstrap endpoint
        list: host 0 is the jax.distributed coordinator)."""
        q = self.cloud.describe(node_id)
        return list(q["node_ips"]) if q else []


# ---------------------------------------------------------------------------
# Replica-capacity providers (serve-pool autoscaler seam)
# ---------------------------------------------------------------------------


class CapacityUnavailable(RuntimeError):
    """The provider cannot grant more capacity right now (stockout /
    configured ceiling). The autoscaler records the denial and keeps
    serving at the current size."""


class ReplicaCapacityProvider:
    """Capacity seam between the serve-pool autoscaler
    (``serve/pool_autoscaler.py``) and whatever actually holds chips.

    The autoscaler never builds a replica out of thin air: it
    ``request()``s capacity, polls ``ready()`` on the returned ticket
    (provisioning a TPU slice takes real minutes; the delay is part
    of the control problem, not an implementation detail), builds the
    replica only once the ticket is ready, and ``release()``s the
    ticket when the replica is later retired. ``eta_s`` is the honest
    remaining-provisioning estimate the pool folds into all-shed
    Retry-After hints so clients are never invited back before
    capacity exists.
    """

    def request(self) -> str:
        """Ask for capacity for ONE replica. Returns an opaque
        ticket. Raises ``CapacityUnavailable`` on a hard denial."""
        raise NotImplementedError

    def ready(self, ticket: str) -> bool:
        """True when the ticket's capacity is provisioned."""
        raise NotImplementedError

    def eta_s(self, ticket: str) -> float:
        """Remaining provisioning time estimate, seconds (0 when
        ready; best-effort floor when the backend is stalled)."""
        return 0.0

    def release(self, ticket: str) -> None:
        """Return the ticket's capacity (scale-down / abandoned
        request). Idempotent."""


class ImmediateCapacityProvider(ReplicaCapacityProvider):
    """Capacity that already exists (spare chips on the host, or unit
    tests): every request is granted and instantly ready, up to an
    optional ceiling of simultaneously-granted tickets."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._granted: set = set()
        self._n = 0

    def request(self) -> str:
        with self._lock:
            if (self._capacity is not None
                    and len(self._granted) >= self._capacity):
                raise CapacityUnavailable(
                    f"capacity ceiling {self._capacity} reached")
            self._n += 1
            ticket = f"immediate-{self._n}"
            self._granted.add(ticket)
            return ticket

    def ready(self, ticket: str) -> bool:
        return True

    def release(self, ticket: str) -> None:
        with self._lock:
            self._granted.discard(ticket)


class TPUSliceCapacityProvider(ReplicaCapacityProvider):
    """One replica == one TPU slice, provisioned through the
    queued-resource lifecycle (``SimulatedTPUCloud`` in CI; a real
    backend implements the same four methods against the Cloud TPU
    API). A ticket is the queued-resource name; ``ready`` polls its
    state to READY, and ``release`` deletes the slice."""

    def __init__(self, cloud: Optional[SimulatedTPUCloud] = None,
                 accelerator_type: str = "v5e-1",
                 name_prefix: str = "pool"):
        if accelerator_type not in TPU_TOPOLOGIES:
            raise ValueError(
                f"unknown accelerator_type {accelerator_type!r}")
        self.cloud = cloud or SimulatedTPUCloud()
        self.accelerator_type = accelerator_type
        self._prefix = name_prefix

    def request(self) -> str:
        name = (f"{self._prefix}-{self.accelerator_type}-"
                f"{uuid.uuid4().hex[:6]}")
        self.cloud.create_queued_resource(name, self.accelerator_type)
        return name

    def ready(self, ticket: str) -> bool:
        q = self.cloud.describe(ticket)
        return bool(q and q["state"] == QR_READY)

    def eta_s(self, ticket: str) -> float:
        q = self.cloud.describe(ticket)
        if q is None:
            return 0.0
        if q["state"] == QR_READY:
            return 0.0
        delay = getattr(self.cloud, "provision_delay_s", 0.0)
        remaining = q["create_time"] + delay - time.time()
        # past the modeled delay but still not READY = stockout; keep
        # a non-zero floor so Retry-After never promises capacity the
        # cloud hasn't granted
        return max(remaining, 0.5)

    def release(self, ticket: str) -> None:
        self.cloud.delete_queued_resource(ticket)


def tpu_node_types(*accelerator_types: str,
                   cpus_per_host: int = 96,
                   max_workers: int = 4) -> Dict[str, Dict[str, Any]]:
    """``available_node_types`` entries for accelerator types: the
    node's resource shape is the WHOLE slice (TPU = total chips), so
    the demand scheduler bin-packs gang demands onto slices."""
    out: Dict[str, Dict[str, Any]] = {}
    for accel in accelerator_types:
        hosts, chips = TPU_TOPOLOGIES[accel]
        out[accel] = {
            "resources": {"TPU": float(hosts * chips),
                          "CPU": float(cpus_per_host * hosts)},
            "max_workers": max_workers,
        }
    return out
