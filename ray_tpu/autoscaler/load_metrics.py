"""LoadMetrics: the autoscaler's view of cluster load.

Capability parity with the reference's LoadMetrics
(python/ray/autoscaler/_private/load_metrics.py:62): per-node static and
available resources, pending resource demands, and last-active
timestamps used for idle-node termination.
"""
from __future__ import annotations

import time
from typing import Dict, List


class NodeLoad:
    def __init__(self, worker_id: str, resources: Dict[str, float],
                 available: Dict[str, float], busy: bool):
        self.worker_id = worker_id
        self.resources = resources
        self.available = available
        self.busy = busy


class LoadMetrics:
    def __init__(self):
        self.pending_demands: List[Dict[str, float]] = []
        self.nodes: Dict[str, NodeLoad] = {}
        self.last_active_at: Dict[str, float] = {}
        self.last_updated = 0.0

    def update(self, snapshot: Dict) -> None:
        """Ingest a HeadService.load_metrics_snapshot() payload."""
        now = time.time()
        self.pending_demands = list(snapshot.get("pending_demands", []))
        self.nodes = {}
        for n in snapshot.get("nodes", []):
            if not n.get("alive", False):
                continue
            # Busy if running work, hosting actors, or holding any
            # resource reservation (e.g. placement-group bundles, which
            # consume availability without a task or actor attached).
            reserved = any(
                n["available"].get(k, 0.0) + 1e-9 < v
                for k, v in n["resources"].items())
            busy = (n.get("num_running_tasks", 0) > 0 or
                    n.get("num_actors", 0) > 0 or reserved)
            wid = n["worker_id"]
            self.nodes[wid] = NodeLoad(wid, dict(n["resources"]),
                                       dict(n["available"]), busy)
            if busy or wid not in self.last_active_at:
                self.last_active_at[wid] = now
        # Forget departed nodes.
        for wid in list(self.last_active_at):
            if wid not in self.nodes:
                del self.last_active_at[wid]
        self.last_updated = now

    def idle_seconds(self, worker_id: str) -> float:
        ts = self.last_active_at.get(worker_id)
        if ts is None:
            return 0.0
        return time.time() - ts

    def summary(self) -> Dict:
        return {
            "num_nodes": len(self.nodes),
            "num_pending_demands": len(self.pending_demands),
            "cluster_resources": _merge(
                [n.resources for n in self.nodes.values()]),
            "available_resources": _merge(
                [n.available for n in self.nodes.values()]),
        }


def _merge(dicts: List[Dict[str, float]]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for d in dicts:
        for k, v in d.items():
            out[k] = out.get(k, 0.0) + v
    return out
