"""Autoscaler: demand-driven, slice-granular cluster scaling.

TPU-native counterpart of python/ray/autoscaler/ (SURVEY.md §2.2 P11):
StandardAutoscaler + bin-packing ResourceDemandScheduler + LoadMetrics +
pluggable NodeProvider, with a process-backed fake provider for e2e
tests. TPU node types are whole ICI slices, so scaling is slice-granular.
"""
from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.sdk import request_resources
from ray_tpu.autoscaler.monitor import AutoscalingCluster, Monitor
from ray_tpu.autoscaler.node_provider import (FakeMultiNodeProvider,
                                              MockProvider, NodeProvider,
                                              TAG_NODE_STATUS,
                                              TAG_NODE_TYPE)
from ray_tpu.autoscaler.resource_demand_scheduler import (
    NodeTypeConfig, get_infeasible_demands, get_nodes_to_launch)

__all__ = [
    "StandardAutoscaler", "LoadMetrics", "Monitor", "AutoscalingCluster",
    "request_resources",
    "NodeProvider", "MockProvider", "FakeMultiNodeProvider",
    "NodeTypeConfig", "get_nodes_to_launch", "get_infeasible_demands",
    "TAG_NODE_TYPE", "TAG_NODE_STATUS",
]
