"""Experiment/trial stopping conditions.

Capability parity with the reference's tune.stopper
(python/ray/tune/stopper/: Stopper ABC with __call__(trial_id,
result) + stop_all(), and the shipped implementations —
MaximumIterationStopper, TimeoutStopper, TrialPlateauStopper,
ExperimentPlateauStopper, CombinedStopper). Wired through
RunConfig(stop=...), which also accepts the reference's dict
({"metric": threshold}) and bare-callable forms.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, Optional, Union


class Stopper:
    """Per-result stopping decision. __call__ returns True to stop
    THAT trial; stop_all() True ends the whole experiment."""

    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def stop_all(self) -> bool:
        return False


class MaximumIterationStopper(Stopper):
    def __init__(self, max_iter: int):
        self.max_iter = int(max_iter)

    def __call__(self, trial_id, result):
        return result.get("training_iteration", 0) >= self.max_iter


class TimeoutStopper(Stopper):
    """Stops the EXPERIMENT after a wall-clock budget, measured from
    the first stopping check (fit() start), not construction."""

    def __init__(self, timeout_s: float):
        self.timeout_s = float(timeout_s)
        self._deadline: Optional[float] = None

    def _arm(self):
        if self._deadline is None:
            self._deadline = time.monotonic() + self.timeout_s

    def __call__(self, trial_id, result):
        self._arm()
        return False

    def stop_all(self) -> bool:
        self._arm()
        return time.monotonic() >= self._deadline


class TrialPlateauStopper(Stopper):
    """Stops a trial whose metric stopped moving: std of the last
    ``num_results`` values below ``std`` (after ``grace_period``
    results)."""

    def __init__(self, metric: str, std: float = 0.01,
                 num_results: int = 4, grace_period: int = 4,
                 mode: Optional[str] = None,
                 metric_threshold: Optional[float] = None):
        self.metric = metric
        self.std = float(std)
        self.num_results = int(num_results)
        self.grace_period = int(grace_period)
        if metric_threshold is not None and mode not in ("min", "max"):
            raise ValueError(
                "metric_threshold requires mode='min' or 'max'")
        self.mode = mode
        self.metric_threshold = metric_threshold
        self._history: Dict[str, collections.deque] = {}
        self._seen: Dict[str, int] = {}

    def __call__(self, trial_id, result):
        if self.metric not in result:
            return False
        h = self._history.setdefault(
            trial_id, collections.deque(maxlen=self.num_results))
        h.append(float(result[self.metric]))
        self._seen[trial_id] = self._seen.get(trial_id, 0) + 1
        if self._seen[trial_id] < self.grace_period or \
                len(h) < self.num_results:
            return False
        if self.metric_threshold is not None:
            # Reference pairing (tune/stopper/trial_plateau.py): the
            # plateau stop applies only to trials whose metric has
            # CONVERGED PAST the threshold — "reached the target and
            # stopped improving". A plateaued-but-bad trial keeps its
            # budget (it may still escape).
            v = float(result[self.metric])
            reached = (v <= self.metric_threshold
                       if self.mode == "min"
                       else v >= self.metric_threshold)
            if not reached:
                return False
        mean = sum(h) / len(h)
        var = sum((v - mean) ** 2 for v in h) / len(h)
        return var ** 0.5 <= self.std


class ExperimentPlateauStopper(Stopper):
    """Ends the experiment when the best value of ``metric`` has not
    improved by more than ``tol`` for ``patience`` consecutive
    results (across ALL trials)."""

    def __init__(self, metric: str, mode: str = "min",
                 tol: float = 0.0, patience: int = 8):
        self.metric = metric
        self.mode = mode
        self.tol = float(tol)
        self.patience = int(patience)
        self._best: Optional[float] = None
        self._stale = 0

    def __call__(self, trial_id, result):
        if self.metric not in result:
            return False
        v = float(result[self.metric])
        improved = self._best is None or (
            v < self._best - self.tol if self.mode == "min"
            else v > self._best + self.tol)
        if improved:
            self._best = v
            self._stale = 0
        else:
            self._stale += 1
        return False

    def stop_all(self) -> bool:
        return self._stale >= self.patience


class CombinedStopper(Stopper):
    def __init__(self, *stoppers: Stopper):
        self.stoppers = stoppers

    def __call__(self, trial_id, result):
        return any(s(trial_id, result) for s in self.stoppers)

    def stop_all(self) -> bool:
        return any(s.stop_all() for s in self.stoppers)


def coerce_stopper(stop: Union[None, Stopper, Callable,
                               Dict[str, Any]]) -> Optional[Stopper]:
    """RunConfig(stop=...) accepts a Stopper, a dict of
    metric->threshold (stop when result[metric] >= threshold, the
    reference's dict form), or a callable(trial_id, result)->bool."""
    if stop is None or isinstance(stop, Stopper):
        return stop
    if isinstance(stop, dict):
        thresholds = dict(stop)

        class _DictStopper(Stopper):
            def __call__(self, trial_id, result):
                return any(k in result and result[k] >= v
                           for k, v in thresholds.items())

        return _DictStopper()
    if callable(stop):
        fn = stop

        class _FnStopper(Stopper):
            def __call__(self, trial_id, result):
                return bool(fn(trial_id, result))

        return _FnStopper()
    raise TypeError(f"stop must be a Stopper, dict, or callable; "
                    f"got {type(stop).__name__}")
