"""Tuner: trial orchestration event loop.

Capability parity with the reference's Tuner/tune.run/TrialRunner
(python/ray/tune/tuner.py:212, tune/tune.py:129,
tune/execution/trial_runner.py:236,864 + ray_trial_executor.py:192): a
searcher proposes configs, trials run as actors under resource limits, every
reported result flows through the scheduler (early stopping / PBT exploits),
checkpoints are tracked per trial, failed trials retry up to max_failures.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.config import RunConfig
from ray_tpu.air.result import Result
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.schedulers import (CONTINUE, STOP, FIFOScheduler,
                                     PopulationBasedTraining,
                                     TrialScheduler)
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trial import (ERROR, PAUSED, PENDING, RUNNING, STOPPED,
                                TERMINATED, Trial)
from ray_tpu.train.worker_group import TrainWorker


@dataclasses.dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Searcher] = None
    resources_per_trial: Optional[Dict[str, float]] = None
    max_failures: int = 0
    time_budget_s: Optional[float] = None


class ResultGrid:
    def __init__(self, trials: List[Trial]):
        self.trials = trials

    def __len__(self):
        return len(self.trials)

    def __getitem__(self, i) -> Result:
        t = self.trials[i]
        return Result(metrics=t.last_result, checkpoint=t.checkpoint,
                      error=t.error,
                      metrics_history=list(t.results))

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or "loss"
        mode = mode or "min"
        best, best_val = None, None
        for t in self.trials:
            if not t.last_result or metric not in t.last_result:
                continue
            # Best across the trial's whole history (a stopped trial may
            # have peaked earlier).
            vals = t.metric_history(metric)
            v = min(vals) if mode == "min" else max(vals)
            if best_val is None or (v < best_val if mode == "min"
                                    else v > best_val):
                best, best_val = t, v
        if best is None:
            raise ValueError(f"No trial reported metric {metric!r}")
        i = self.trials.index(best)
        return self[i]

    @property
    def errors(self) -> List[BaseException]:
        return [t.error for t in self.trials if t.error is not None]


class Tuner:
    def __init__(self, trainable: Callable,
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._fn = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restored_trials: List[Trial] = []

    # --- trial process management -----------------------------------------

    def _actor_options(self) -> Dict[str, Any]:
        res = dict(self.tune_config.resources_per_trial or {"CPU": 1})
        opts: Dict[str, Any] = {"max_concurrency": 2}
        opts["num_cpus"] = res.pop("CPU", 1)
        opts["num_tpus"] = res.pop("TPU", 0)
        if res:
            opts["resources"] = res
        return opts

    def _start_trial(self, trial: Trial,
                     resume_checkpoint=None) -> None:
        actor_cls = ray_tpu.remote(TrainWorker)
        handle = actor_cls.options(**self._actor_options()).remote(0, 1)
        trial.runtime_handle = handle
        trial.run_ref = handle.run.remote(
            self._fn, trial.config, None,
            resume_checkpoint if resume_checkpoint is not None
            else trial.checkpoint)
        trial.state = RUNNING

    def _stop_trial(self, trial: Trial, state: str):
        trial.state = state
        if trial.runtime_handle is not None:
            try:
                ray_tpu.kill(trial.runtime_handle)
            except Exception:
                pass
            trial.runtime_handle = None

    # --- the event loop ---------------------------------------------------

    def fit(self) -> ResultGrid:
        from ray_tpu._private.usage_stats import record_library_usage
        record_library_usage("tune")
        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler(tc.metric, tc.mode)
        searcher = tc.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=tc.num_samples)

        from ray_tpu.tune.stopper import coerce_stopper
        stopper = coerce_stopper(getattr(self.run_config, "stop",
                                         None))

        trials: List[Trial] = list(self._restored_trials)
        # A restored experiment re-runs its unfinished trials; the
        # search budget was already spent in the original run.
        searcher_done = bool(self._restored_trials)
        suggest_seq = 0
        suggest_ids: Dict[str, str] = {}   # trial_id -> suggest id
        finished_ids: set = set()

        def finish(trial: Trial):
            """Searcher bookkeeping for EVERY terminal path (normal
            completion, error exhaustion, scheduler stop, time
            budget): feed the observation, then release the suggest
            slot (ConcurrencyLimiter capacity / Repeater groups)."""
            if trial.trial_id in finished_ids:
                return
            finished_ids.add(trial.trial_id)
            self._observe(searcher, trial, tc)
            release = getattr(searcher, "release", None)
            if release is not None:
                release(suggest_ids.get(trial.trial_id))

        start_time = time.time()
        stop_experiment = False
        while True:
            running = [t for t in trials if t.state == RUNNING]
            pending = [t for t in trials if t.state == PENDING]
            # Suggest lazily as slots free up so model-based searchers
            # (TPE) see completed-trial observations before proposing
            # (reference: TrialRunner pulls from the search algorithm
            # incrementally, not up front).
            while not searcher_done and \
                    len(running) + len(pending) < \
                    tc.max_concurrent_trials:
                sid = f"t{suggest_seq}"
                cfg = searcher.suggest(sid)
                if cfg is None:
                    # None means exhausted UNLESS the searcher reports
                    # it is merely backpressured (ConcurrencyLimiter).
                    fin = getattr(searcher, "is_finished", None)
                    if fin is None or fin():
                        searcher_done = True
                    break
                suggest_seq += 1
                t = Trial(config=cfg)
                suggest_ids[t.trial_id] = sid
                trials.append(t)
                pending.append(t)
            # Launch up to the concurrency cap.
            while pending and len(running) < tc.max_concurrent_trials:
                t = pending.pop(0)
                self._start_trial(t)
                running.append(t)
            if not running:
                break

            made_progress = False
            for trial in running:
                poll = ray_tpu.get(
                    trial.runtime_handle.poll.remote())
                for metrics, ckpt in poll["reports"]:
                    made_progress = True
                    metrics = dict(metrics)
                    metrics.setdefault("training_iteration",
                                       len(trial.results) + 1)
                    trial.results.append(metrics)
                    trial.last_result = metrics
                    if ckpt is not None:
                        trial.checkpoint = ckpt
                    decision = scheduler.on_result(trial, metrics,
                                                   trials)
                    stopper_says = False
                    if stopper is not None:
                        stopper_says = stopper(trial.trial_id,
                                               metrics)
                        # Reports arrive in bursts (a fast trial can
                        # deliver many per poll), so the experiment-
                        # wide condition must be consulted per result
                        # too, not just once per event-loop pass.
                        if stopper.stop_all():
                            stop_experiment = True
                    if decision == STOP or stopper_says or \
                            stop_experiment:
                        self._stop_trial(trial, STOPPED)
                        finish(trial)
                        break
                if trial.state != RUNNING:
                    continue
                # PBT exploit?
                if isinstance(scheduler, PopulationBasedTraining):
                    exploit = scheduler.pending_exploits.pop(
                        trial.trial_id, None)
                    if exploit is not None:
                        made_progress = True
                        self._stop_trial(trial, PAUSED)
                        trial.config = exploit["config"]
                        trial.checkpoint = exploit["checkpoint"]
                        self._start_trial(trial)
                        continue
                if poll["done"]:
                    made_progress = True
                    if poll["error"] is not None:
                        if trial.restarts < tc.max_failures:
                            trial.restarts += 1
                            self._start_trial(trial)
                        else:
                            trial.error = poll["error"]
                            self._stop_trial(trial, ERROR)
                            scheduler.on_trial_complete(trial, trials)
                    else:
                        self._stop_trial(trial, TERMINATED)
                        scheduler.on_trial_complete(trial, trials)
                    finish(trial)
                    self._save_experiment_state(trials)

            over_budget = tc.time_budget_s is not None and \
                time.time() - start_time > tc.time_budget_s
            if over_budget or stop_experiment or (
                    stopper is not None and stopper.stop_all()):
                for t in trials:
                    if not t.finished:
                        self._stop_trial(t, STOPPED)
                        finish(t)
                break
            if not made_progress:
                time.sleep(0.01)
        self._save_experiment_state(trials)
        return ResultGrid(trials)

    # --- searcher feedback + experiment persistence -----------------------

    @staticmethod
    def _observe(searcher, trial: Trial, tc: TuneConfig):
        """Feed the completed trial back to model-based searchers
        (budget-aware ones also learn the fidelity it reached)."""
        observe = getattr(searcher, "observe", None)
        if observe is None or not trial.results:
            return
        vals = trial.metric_history(tc.metric)
        if not vals:
            return
        best = min(vals) if tc.mode == "min" else max(vals)
        import inspect
        try:
            takes_budget = "budget" in \
                inspect.signature(observe).parameters
        except (TypeError, ValueError):
            takes_budget = False
        if takes_budget:
            observe(trial.config, best, budget=len(vals))
        else:
            observe(trial.config, best)

    def _state_path(self) -> Optional[str]:
        import os
        if not self.run_config.storage_path:
            return None
        name = self.run_config.name or "tune_experiment"
        d = os.path.join(self.run_config.storage_path, name)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, "experiment_state.pkl")

    def _save_experiment_state(self, trials: List[Trial]):
        """Reference: TrialRunner checkpointing + Syncer — trial state
        and latest checkpoints persist under storage_path so the
        experiment is resumable (tune.run(resume=...))."""
        path = self._state_path()
        if path is None:
            return
        import pickle
        blob = []
        for t in trials:
            blob.append({
                "trial_id": t.trial_id,
                "config": t.config,
                "state": t.state,
                "results": t.results,
                "checkpoint": (t.checkpoint.to_dict()
                               if t.checkpoint is not None else None),
                "error": repr(t.error) if t.error else None,
            })
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(blob, f)
        import os
        os.replace(tmp, path)

    @classmethod
    def restore(cls, path: str, trainable: Callable,
                restart_errored: bool = True,
                **tuner_kwargs) -> "Tuner":
        """Resume an experiment: finished trials keep their results;
        unfinished — and, by default, errored — trials are re-queued
        (reference: Tuner.restore(restart_errored=...) /
        tune.run(resume=True))."""
        import os
        import pickle
        from ray_tpu.air.checkpoint import Checkpoint
        state_file = path if path.endswith(".pkl") else os.path.join(
            path, "experiment_state.pkl")
        with open(state_file, "rb") as f:
            blob = pickle.load(f)
        tuner = cls(trainable, **tuner_kwargs)
        restored: List[Trial] = []
        for rec in blob:
            t = Trial(config=rec["config"], trial_id=rec["trial_id"])
            t.results = rec["results"]
            t.last_result = rec["results"][-1] if rec["results"] else None
            if rec["checkpoint"] is not None:
                t.checkpoint = Checkpoint.from_dict(rec["checkpoint"])
            keep = (TERMINATED, STOPPED) if restart_errored else \
                (TERMINATED, ERROR, STOPPED)
            if rec["state"] in keep:
                t.state = rec["state"]
            else:
                t.state = PENDING   # re-run unfinished/errored trials
                t.results = []
            restored.append(t)
        tuner._restored_trials = restored
        return tuner
