"""Trial: one configuration's lifecycle.

Capability parity with the reference's Trial (python/ray/tune/experiment/
trial.py state machine) reduced to the states the runner drives:
PENDING → RUNNING → (TERMINATED | ERROR | STOPPED), PAUSED for PBT.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint

_ids = itertools.count()

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"
STOPPED = "STOPPED"       # stopped early by a scheduler


@dataclasses.dataclass
class Trial:
    config: Dict[str, Any]
    trial_id: str = dataclasses.field(
        default_factory=lambda: f"trial_{next(_ids):05d}")
    state: str = PENDING
    last_result: Optional[Dict[str, Any]] = None
    results: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    restarts: int = 0
    # Runner bookkeeping (actor handle + pending run ref).
    runtime_handle: Any = None
    run_ref: Any = None

    def metric_history(self, metric: str) -> List[float]:
        return [r[metric] for r in self.results if metric in r]

    @property
    def finished(self) -> bool:
        return self.state in (TERMINATED, ERROR, STOPPED)
