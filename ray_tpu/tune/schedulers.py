"""Trial schedulers.

Capability parity with the reference's tune.schedulers: FIFO
(schedulers/trial_scheduler.py), ASHA (async_hyperband.py), median stopping
(median_stopping_rule.py), PBT (pbt.py). Decisions are made on each
reported result; PBT additionally exploits/explores through checkpoints.
"""
from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def __init__(self, metric: str = "loss", mode: str = "min"):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode

    def _sign(self, value: float) -> float:
        return -value if self.mode == "max" else value

    def on_result(self, trial: Trial, result: Dict[str, Any],
                  all_trials: List[Trial]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial, all_trials: List[Trial]):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving (reference:
    tune/schedulers/async_hyperband.py). Rungs at grace_period *
    reduction_factor^k; a trial stops at a rung if it is not in the top
    1/reduction_factor of completed results at that rung."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 4,
                 max_t: int = 100):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # rung milestone -> list of recorded metric values
        self.rungs: Dict[int, List[float]] = {}
        m = grace_period
        while m < max_t:
            self.rungs[m] = []
            m *= reduction_factor

    def on_result(self, trial, result, all_trials) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        for milestone in sorted(self.rungs):
            if t == milestone:
                recorded = self.rungs[milestone]
                recorded.append(self._sign(float(value)))
                k = max(1, len(recorded) // self.rf)
                cutoff = sorted(recorded)[k - 1]
                if self._sign(float(value)) > cutoff:
                    return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required

    def on_result(self, trial, result, all_trials) -> str:
        t = result.get(self.time_attr, 0)
        if t < self.grace:
            return CONTINUE
        averages = []
        for other in all_trials:
            if other.trial_id == trial.trial_id:
                continue
            hist = [self._sign(v)
                    for v in other.metric_history(self.metric)]
            if hist:
                averages.append(sum(hist) / len(hist))
        if len(averages) < self.min_samples:
            return CONTINUE
        median = sorted(averages)[len(averages) // 2]
        best = min(self._sign(v)
                   for v in trial.metric_history(self.metric))
        return STOP if best > median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): at each
    perturbation_interval, bottom-quantile trials clone the checkpoint of
    a top-quantile trial and continue with mutated hyperparameters. The
    runner performs the actual exploit via trial.checkpoint."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._rng = random.Random(seed)
        # trial_id -> exploit instruction for the runner
        self.pending_exploits: Dict[str, Dict[str, Any]] = {}

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                new[key] = spec()
            elif isinstance(spec, list):
                new[key] = self._rng.choice(spec)
            else:  # numeric: perturb by 0.8x / 1.2x
                factor = self._rng.choice([0.8, 1.2])
                new[key] = config.get(key, spec) * factor
        return new

    def on_result(self, trial, result, all_trials) -> str:
        t = result.get(self.time_attr, 0)
        if t == 0 or t % self.interval != 0:
            return CONTINUE
        scored = [(self._sign(x.last_result[self.metric]), x)
                  for x in all_trials
                  if x.last_result and self.metric in x.last_result]
        if len(scored) < 2:
            return CONTINUE
        scored.sort(key=lambda p: p[0])
        n = max(1, int(len(scored) * self.quantile))
        top = [x for _, x in scored[:n]]
        bottom_ids = {x.trial_id for _, x in scored[-n:]}
        if trial.trial_id in bottom_ids:
            donor = self._rng.choice(top)
            if donor.trial_id != trial.trial_id and \
                    donor.checkpoint is not None:
                self.pending_exploits[trial.trial_id] = {
                    "config": self._mutate(donor.config),
                    "checkpoint": donor.checkpoint,
                }
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand-style successive halving (reference:
    tune/schedulers/hyperband.py; the async variant is
    AsyncHyperBandScheduler). Rungs at r, r*eta, r*eta^2, ...; when all
    live trials have reached a rung, the bottom 1-1/eta fraction stops.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 81, reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        super().__init__(metric, mode)
        self.max_t = max_t
        self.eta = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        r = 1
        while r < max_t:
            self.rungs.append(r)
            r *= reduction_factor

    def on_result(self, trial, result, trials) -> str:
        t = result.get(self.time_attr, len(trial.results))
        if t >= self.max_t:
            return STOP
        # Find the highest rung this trial just reached.
        reached = [r for r in self.rungs if t >= r]
        if not reached:
            return CONTINUE
        rung = reached[-1]
        live = [tr for tr in trials if not tr.finished]
        # Synchronous: decide only once every live trial reached the rung.
        at_rung = [tr for tr in live
                   if len(tr.metric_history(self.metric)) >= rung]
        if len(at_rung) < len(live) or len(at_rung) < 2:
            return CONTINUE
        scores = []
        for tr in at_rung:
            vals = tr.metric_history(self.metric)[:rung]
            s = min(vals) if self.mode == "min" else max(vals)
            scores.append((s, tr.trial_id))
        scores.sort(reverse=(self.mode == "max"))
        keep = max(1, len(scores) // self.eta)
        survivors = {tid for _, tid in scores[:keep]}
        return CONTINUE if trial.trial_id in survivors else STOP
