from ray_tpu.tune.search import (choice, grid_search, loguniform, qrandint,
                                 randint, uniform, BasicVariantGenerator,
                                 BOHBSearcher, ConcurrencyLimiter,
                                 Repeater, Searcher, SearcherAdapter,
                                 TPESearcher)
from ray_tpu.tune.schedulers import (AsyncHyperBandScheduler, FIFOScheduler,
                                     HyperBandScheduler,
                                     MedianStoppingRule,
                                     PopulationBasedTraining)
from ray_tpu.tune.stopper import (CombinedStopper,
                                  ExperimentPlateauStopper,
                                  MaximumIterationStopper, Stopper,
                                  TimeoutStopper, TrialPlateauStopper)
from ray_tpu.tune.tuner import TuneConfig, Tuner, ResultGrid
from ray_tpu.tune.trial import Trial

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "Trial",
    "grid_search", "choice", "uniform", "loguniform", "randint",
    "qrandint", "BasicVariantGenerator", "TPESearcher",
    "BOHBSearcher", "Searcher", "SearcherAdapter",
    "ConcurrencyLimiter", "Repeater",
    "Stopper", "MaximumIterationStopper", "TimeoutStopper",
    "TrialPlateauStopper", "ExperimentPlateauStopper",
    "CombinedStopper",
    "FIFOScheduler", "AsyncHyperBandScheduler", "HyperBandScheduler",
    "MedianStoppingRule", "PopulationBasedTraining",
]


def run(trainable, *, config=None, num_samples: int = 1,
        metric: str = "loss", mode: str = "min", search_alg=None,
        scheduler=None, max_concurrent_trials: int = 4,
        resources_per_trial=None, storage_path=None, name=None,
        time_budget_s=None):
    """Functional entry point (reference: tune.run) — a thin wrapper
    over Tuner(...).fit() returning the ResultGrid. The Tuner API is
    the primary surface; this exists for the classic call shape."""
    from ray_tpu.air import RunConfig
    tc = TuneConfig(metric=metric, mode=mode, search_alg=search_alg,
                    scheduler=scheduler,
                    max_concurrent_trials=max_concurrent_trials,
                    num_samples=num_samples,
                    resources_per_trial=resources_per_trial,
                    time_budget_s=time_budget_s)
    return Tuner(trainable, param_space=config, tune_config=tc,
                 run_config=RunConfig(storage_path=storage_path,
                                      name=name)).fit()


__all__.append("run")
