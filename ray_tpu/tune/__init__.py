from ray_tpu.tune.search import (choice, grid_search, loguniform, qrandint,
                                 randint, uniform, BasicVariantGenerator,
                                 BOHBSearcher, Searcher, SearcherAdapter,
                                 TPESearcher)
from ray_tpu.tune.schedulers import (AsyncHyperBandScheduler, FIFOScheduler,
                                     HyperBandScheduler,
                                     MedianStoppingRule,
                                     PopulationBasedTraining)
from ray_tpu.tune.tuner import TuneConfig, Tuner, ResultGrid
from ray_tpu.tune.trial import Trial

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "Trial",
    "grid_search", "choice", "uniform", "loguniform", "randint",
    "qrandint", "BasicVariantGenerator", "TPESearcher",
    "BOHBSearcher", "Searcher", "SearcherAdapter",
    "FIFOScheduler", "AsyncHyperBandScheduler", "HyperBandScheduler",
    "MedianStoppingRule", "PopulationBasedTraining",
]
