"""Search spaces and variant generation.

Capability parity with the reference's tune.search (sample domains
python/ray/tune/search/sample.py, grid/variant expansion
search/basic_variant.py + variant_generator.py). Pluggable Searcher
interface mirrors search/searcher.py so external algorithms (optuna-style)
can be adapted.
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math
        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class QRandInt(Domain):
    def __init__(self, low: int, high: int, q: int):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        return (rng.randrange(self.low, self.high) // self.q) * self.q


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def qrandint(low, high, q) -> QRandInt:
    return QRandInt(low, high, q)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values: List[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


class Searcher:
    """Suggest/observe interface (reference: tune/search/searcher.py)."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid expansion x num_samples random sampling."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self._rng = random.Random(seed)
        self._variants = self._expand()
        self._idx = 0

    def _expand(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if _is_grid(v)]
        grid_values = [self.param_space[k]["grid_search"]
                       for k in grid_keys]
        variants = []
        for _ in range(self.num_samples):
            for combo in itertools.product(*grid_values) if grid_keys \
                    else [()]:
                cfg = {}
                for k, v in self.param_space.items():
                    if k in grid_keys:
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                variants.append(cfg)
        return variants

    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg
