"""Search spaces and variant generation.

Capability parity with the reference's tune.search (sample domains
python/ray/tune/search/sample.py, grid/variant expansion
search/basic_variant.py + variant_generator.py). Pluggable Searcher
interface mirrors search/searcher.py so external algorithms (optuna-style)
can be adapted.
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math
        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class QRandInt(Domain):
    def __init__(self, low: int, high: int, q: int):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        return (rng.randrange(self.low, self.high) // self.q) * self.q


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def qrandint(low, high, q) -> QRandInt:
    return QRandInt(low, high, q)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values: List[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


class Searcher:
    """Suggest/observe interface (reference: tune/search/searcher.py —
    the same contract external integrations implement there: suggest,
    on_trial_result, on_trial_complete, save/restore, and
    set_search_properties)."""

    metric: Optional[str] = None
    mode: str = "min"

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str,
                        result: Optional[Dict] = None):
        """Intermediate result (multi-fidelity searchers use these)."""

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False):
        pass

    def is_finished(self) -> bool:
        """Whether None from suggest() means exhausted (True, the
        default) or mere backpressure (ConcurrencyLimiter returns
        False while slots are full). The trial runner keeps polling a
        not-finished searcher as slots free up."""
        return True

    def release(self, trial_id: Optional[str]) -> None:
        """Called by the trial runner when the trial for a suggest id
        reaches a terminal state (on EVERY terminal path: completion,
        error exhaustion, scheduler stop, time budget). Wrappers use
        it to free capacity / close repeat groups."""

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str],
                              config: Optional[Dict[str, Any]] = None
                              ) -> bool:
        """Late-bind objective/space from TuneConfig (reference:
        searcher.py set_search_properties). Returns True if applied."""
        if metric is not None:
            self.metric = metric
        if mode is not None:
            self.mode = mode
        if config and not getattr(self, "param_space", None):
            self.param_space = dict(config)
        return True

    # -- persistence (experiment resume restores searcher state) ----------

    def save(self, path: str) -> None:
        import pickle
        with open(path, "wb") as f:
            pickle.dump(self.__dict__, f)

    def restore(self, path: str) -> None:
        import pickle
        with open(path, "rb") as f:
            self.__dict__.update(pickle.load(f))


class SearcherAdapter(Searcher):
    """Bridge an EXTERNAL ask/tell optimizer into the Searcher
    contract (the plugin seam the reference fills per-library under
    tune/search/{optuna,hyperopt,...}; one adapter here because every
    modern optimizer exposes ask/tell).

    `ext` must provide ask() -> config dict and tell(config, value);
    mode handling: values are negated for mode='max' before tell when
    `minimizing` (the usual external convention) is True."""

    def __init__(self, ext, metric: str, mode: str = "min",
                 num_samples: int = 16, minimizing: bool = True):
        self.ext = ext
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.minimizing = minimizing
        self._suggested = 0
        self._configs: Dict[str, Dict[str, Any]] = {}

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        cfg = dict(self.ext.ask())
        self._configs[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False):
        cfg = self._configs.pop(trial_id, None)
        if cfg is None or error or not result or \
                self.metric not in result:
            return
        v = float(result[self.metric])
        if self.mode == "max" and self.minimizing:
            v = -v
        self.ext.tell(cfg, v)

    def observe(self, config: Dict[str, Any], value: float):
        v = float(value)
        if self.mode == "max" and self.minimizing:
            v = -v
        self.ext.tell(dict(config), v)

    def save(self, path: str) -> None:
        import pickle
        with open(path, "wb") as f:
            pickle.dump({"suggested": self._suggested,
                         "configs": self._configs,
                         "ext": self.ext}, f)

    def restore(self, path: str) -> None:
        import pickle
        with open(path, "rb") as f:
            st = pickle.load(f)
        self._suggested = st["suggested"]
        self._configs = st["configs"]
        self.ext = st["ext"]


class BasicVariantGenerator(Searcher):
    """Grid expansion x num_samples random sampling."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self._rng = random.Random(seed)
        self._variants = self._expand()
        self._idx = 0

    def _expand(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if _is_grid(v)]
        grid_values = [self.param_space[k]["grid_search"]
                       for k in grid_keys]
        variants = []
        for _ in range(self.num_samples):
            for combo in itertools.product(*grid_values) if grid_keys \
                    else [()]:
                cfg = {}
                for k, v in self.param_space.items():
                    if k in grid_keys:
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                variants.append(cfg)
        return variants

    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator searcher (the Optuna-default
    algorithm; reference integrates it via tune/search/optuna/).

    After ``n_startup`` random trials, completed trials are split into
    good/bad halves by objective; candidates are sampled from a kernel
    density around good observations and scored by the density ratio
    l(x)/g(x) (Bergstra et al. 2011), per independent dimension.
    """

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: str = "min", num_samples: int = 16,
                 n_startup: int = 5, n_candidates: int = 24,
                 gamma: float = 0.33, seed: int = 0):
        self.param_space = dict(param_space)
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.gamma = gamma
        self._rng = random.Random(seed)
        self._suggested = 0
        self._observed: List[Tuple[Dict[str, Any], float]] = []

    # -- domain helpers ----------------------------------------------------

    def _random_config(self) -> Dict[str, Any]:
        out = {}
        for k, v in self.param_space.items():
            out[k] = v.sample(self._rng) if isinstance(v, Domain) else v
        return out

    def _numeric(self, dom) -> bool:
        return isinstance(dom, (Uniform, LogUniform, RandInt, QRandInt))

    def _kde_score(self, values: List[float], x: float,
                   bandwidth: float) -> float:
        if not values:
            return 1e-12
        import math
        return sum(
            math.exp(-0.5 * ((x - v) / bandwidth) ** 2)
            for v in values) / len(values) + 1e-12

    def _suggest_tpe(self) -> Dict[str, Any]:
        ranked = sorted(self._observed, key=lambda o: o[1],
                        reverse=(self.mode == "max"))
        n_good = max(1, int(len(ranked) * self.gamma))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        best, best_score = None, -1.0
        for _ in range(self.n_candidates):
            cand = self._random_config()
            score = 1.0
            for k, dom in self.param_space.items():
                if isinstance(dom, LogUniform):
                    import math
                    tx = math.log(cand[k])
                    gv = [math.log(c[k]) for c in good]
                    bv = [math.log(c[k]) for c in bad]
                    bw = max((math.log(dom.high) -
                              math.log(dom.low)) / 8, 1e-6)
                elif self._numeric(dom):
                    tx = float(cand[k])
                    gv = [float(c[k]) for c in good]
                    bv = [float(c[k]) for c in bad]
                    span = float(getattr(dom, "high", 1) -
                                 getattr(dom, "low", 0))
                    bw = max(span / 8, 1e-6)
                elif isinstance(dom, Choice):
                    gcnt = sum(1 for c in good if c[k] == cand[k])
                    bcnt = sum(1 for c in bad if c[k] == cand[k])
                    score *= ((gcnt + 1) / (len(good) + 1)) / \
                        ((bcnt + 1) / (len(bad) + 1))
                    continue
                else:
                    continue
                score *= self._kde_score(gv, tx, bw) / \
                    self._kde_score(bv, tx, bw)
            if score > best_score:
                best, best_score = cand, score
        return best or self._random_config()

    # -- Searcher interface ------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        if len(self._observed) < self.n_startup:
            return self._random_config()
        return self._suggest_tpe()

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None):
        if result and self.metric in result:
            config = result.get("config")
            if config is not None:
                self._observed.append((config, result[self.metric]))

    def observe(self, config: Dict[str, Any], value: float):
        """Direct observation hook (used by the trial runner)."""
        self._observed.append((dict(config), value))


class BOHBSearcher(TPESearcher):
    """BOHB's model half (Falkner et al. 2018): TPE model built from
    the HIGHEST budget that has enough observations, paired with the
    bandit half — HyperBandScheduler's brackets — for early stopping.
    (Reference integrates this as tune/search/bohb/ TuneBOHB +
    HyperBandForBOHB.)

    Observations are recorded per budget (training iterations seen);
    suggest() fits the KDE on the largest budget with >= n_min points,
    falling back to lower budgets, then to random — so the model
    always uses the highest-fidelity evidence available, the core
    BOHB idea."""

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: str = "min", num_samples: int = 16,
                 n_startup: int = 5, n_candidates: int = 24,
                 gamma: float = 0.33, seed: int = 0, n_min: int = 4):
        super().__init__(param_space, metric, mode=mode,
                         num_samples=num_samples, n_startup=n_startup,
                         n_candidates=n_candidates, gamma=gamma,
                         seed=seed)
        self.n_min = n_min
        # budget -> [(config, value), ...]
        self._by_budget: Dict[int, List[Tuple[Dict[str, Any], float]]]\
            = {}

    def observe(self, config: Dict[str, Any], value: float,
                budget: int = 1):
        self._by_budget.setdefault(int(budget), []).append(
            (dict(config), float(value)))

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        if error or not result or self.metric not in result:
            return
        config = result.get("config")
        if config is not None:
            self.observe(config, result[self.metric],
                         result.get("training_iteration", 1))

    def _model_budget(self) -> Optional[int]:
        for b in sorted(self._by_budget, reverse=True):
            if len(self._by_budget[b]) >= self.n_min:
                return b
        return None

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        b = self._model_budget()
        if b is None:
            return self._random_config()
        # Point the parent's KDE machinery at the chosen budget's
        # observations for this one suggestion.
        self._observed = self._by_budget[b]
        return self._suggest_tpe()


def _forward_observe(searcher, config: Dict[str, Any], value: float,
                     budget: Optional[int] = None):
    """Forward an observation to a wrapped searcher, passing budget
    through only when its observe() accepts one (BOHB's multi-fidelity
    model needs it; TPE's does not)."""
    fwd = getattr(searcher, "observe", None)
    if fwd is None:
        return
    if budget is not None:
        import inspect
        try:
            if "budget" in inspect.signature(fwd).parameters:
                fwd(dict(config), value, budget=budget)
                return
        except (TypeError, ValueError):
            pass
    fwd(dict(config), value)


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions from a wrapped searcher (reference:
    tune/search/concurrency_limiter.py). suggest() returns None while
    the cap is reached — backpressure, not exhaustion; the trial
    runner distinguishes the two via is_finished()."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = int(max_concurrent)
        self._live: set = set()
        self._finished = False
        self.metric = getattr(searcher, "metric", None)
        self.mode = getattr(searcher, "mode", "min")

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._finished or len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is None:
            self._finished = True
            return None
        self._live.add(trial_id)
        return cfg

    def is_finished(self) -> bool:
        return self._finished

    def release(self, trial_id: Optional[str]):
        self._live.discard(trial_id)
        inner = getattr(self.searcher, "release", None)
        if inner is not None:
            inner(trial_id)

    def observe(self, config: Dict[str, Any], value: float,
                budget: Optional[int] = None):
        _forward_observe(self.searcher, config, value, budget)

    def set_search_properties(self, metric, mode, config=None) -> bool:
        ok = self.searcher.set_search_properties(metric, mode, config)
        self.metric = getattr(self.searcher, "metric", metric)
        self.mode = getattr(self.searcher, "mode", mode or "min")
        return ok


class Repeater(Searcher):
    """Evaluate each suggested config `repeat` times and feed the MEAN
    objective back to the wrapped searcher (reference:
    tune/search/repeater.py — de-noises stochastic objectives so the
    model doesn't chase seed luck).

    Group accounting rides release(): the runner releases every trial
    on every terminal path, so a repeat that errors without reporting
    still closes its slot and the group flushes with the values that
    did arrive. (Limitation: a scheduler that REWRITES trial.config
    mid-flight, e.g. a PBT exploit, makes that repeat's observation
    land outside its group; the group still flushes on release with
    the remaining repeats.)"""

    def __init__(self, searcher: Searcher, repeat: int = 3):
        self.searcher = searcher
        self.repeat = int(repeat)
        self._current: Optional[Dict[str, Any]] = None
        self._handed_out = 0
        self._finished = False
        self._pending: Dict[str, List[float]] = {}
        self._budgets: Dict[str, int] = {}
        self._done_counts: Dict[str, int] = {}
        self._sid2key: Dict[str, str] = {}
        self.metric = getattr(searcher, "metric", None)
        self.mode = getattr(searcher, "mode", "min")

    @staticmethod
    def _key(config: Dict[str, Any]) -> str:
        import json
        return json.dumps(config, sort_keys=True, default=str)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._finished:
            return None
        if self._current is None or self._handed_out >= self.repeat:
            cfg = self.searcher.suggest(trial_id)
            if cfg is None:
                self._finished = True
                return None
            self._current, self._handed_out = cfg, 0
            self._pending.setdefault(self._key(cfg), [])
        self._handed_out += 1
        self._sid2key[trial_id] = self._key(self._current)
        return dict(self._current)

    def is_finished(self) -> bool:
        return self._finished

    def observe(self, config: Dict[str, Any], value: float,
                budget: Optional[int] = None):
        k = self._key(config)
        self._pending.setdefault(k, []).append(float(value))
        if budget is not None:
            self._budgets[k] = max(self._budgets.get(k, 0),
                                   int(budget))

    def release(self, trial_id: Optional[str]):
        k = self._sid2key.pop(trial_id, None)
        if k is None:
            return
        self._done_counts[k] = self._done_counts.get(k, 0) + 1
        if self._done_counts[k] < self.repeat:
            return
        del self._done_counts[k]
        vals = self._pending.pop(k, [])
        budget = self._budgets.pop(k, None)
        if vals:
            import json
            _forward_observe(self.searcher, json.loads(k),
                             sum(vals) / len(vals), budget)

    def set_search_properties(self, metric, mode, config=None) -> bool:
        ok = self.searcher.set_search_properties(metric, mode, config)
        self.metric = getattr(self.searcher, "metric", metric)
        self.mode = getattr(self.searcher, "mode", mode or "min")
        return ok
