from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.air import session

__all__ = ["Checkpoint", "ScalingConfig", "RunConfig", "FailureConfig",
           "CheckpointConfig", "Result", "session"]
