from ray_tpu.air.checkpoint import Checkpoint, InvalidCheckpointError
from ray_tpu.air.checkpoint_manager import CheckpointManager
from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.air import session

__all__ = ["Checkpoint", "CheckpointManager", "InvalidCheckpointError",
           "ScalingConfig", "RunConfig", "FailureConfig",
           "CheckpointConfig", "Result", "session"]
