"""Training session: the worker-side API inside a train loop.

Capability parity with the reference's ``session.report`` pipeline
(python/ray/air/session.py:12 → train/_internal/session.py:261): the user
loop calls ``report(metrics, checkpoint=)``; results stream to the trainer.
Also exposes rank/world/mesh context for SPMD loops.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint

_ctx = threading.local()


class TrainContext:
    def __init__(self, world_rank: int, world_size: int,
                 report_fn, mesh=None, trial_info: Optional[Dict] = None,
                 checkpoint: Optional[Checkpoint] = None,
                 config: Optional[Dict[str, Any]] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 heartbeat_fn=None, preempt_fn=None,
                 attempt: int = 0):
        self.world_rank = world_rank
        self.world_size = world_size
        self.report_fn = report_fn
        self.mesh = mesh
        self.trial_info = trial_info or {}
        self.loaded_checkpoint = checkpoint
        self.config = config or {}
        self.datasets = datasets or {}
        self.attempt = attempt
        # Gang-supervision hooks (set by TrainWorker): touch the
        # progress heartbeat / read the preemption notice.
        self.heartbeat_fn = heartbeat_fn
        self.preempt_fn = preempt_fn


def _require_ctx() -> TrainContext:
    ctx = getattr(_ctx, "ctx", None)
    if ctx is None:
        raise RuntimeError(
            "session API used outside a train loop (no active session)")
    return ctx


def in_session() -> bool:
    return getattr(_ctx, "ctx", None) is not None


def set_context(ctx: Optional[TrainContext]):
    _ctx.ctx = ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) to the trainer.
    Counts as progress for the gang heartbeat deadline."""
    ctx = _require_ctx()
    if ctx.heartbeat_fn is not None:
        ctx.heartbeat_fn()
    ctx.report_fn(dict(metrics), checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from (set on restart), else None."""
    return _require_ctx().loaded_checkpoint


def get_attempt() -> int:
    """The trainer-assigned attempt id of this gang: 0 for the first
    launch, incremented on every elastic restart. Monotonic across the
    whole fit, which makes it a fencing token — a loop superseded by a
    restart can compare its attempt against the newest started one."""
    return _require_ctx().attempt


def heartbeat() -> None:
    """Touch this worker's progress heartbeat without reporting
    metrics. Long steps (big compiles, slow data fetches) call this so
    the trainer's progress deadline doesn't mistake them for a hang;
    ``report()`` touches it implicitly."""
    ctx = _require_ctx()
    if ctx.heartbeat_fn is not None:
        ctx.heartbeat_fn()


def preempted() -> bool:
    """True once a preemption notice has been delivered to this gang:
    the slice is going away after a grace window. A well-behaved loop
    checkpoints immediately and returns (drains); the trainer then
    resumes elastically on whatever capacity remains."""
    ctx = _require_ctx()
    return bool(ctx.preempt_fn()) if ctx.preempt_fn is not None else False


def get_world_rank() -> int:
    return _require_ctx().world_rank


def get_world_size() -> int:
    return _require_ctx().world_size


def get_mesh():
    """The jax device mesh built for this gang (None for CPU loops)."""
    return _require_ctx().mesh


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed to the trainer via
    ``datasets={name: ds}`` (reference: session.get_dataset_shard —
    equal-row shards, iterate with iter_batches /
    iter_torch_batches / iter_device_batches)."""
    ctx = _require_ctx()
    if name not in ctx.datasets:
        raise KeyError(
            f"no dataset {name!r} was passed to the trainer "
            f"(available: {sorted(ctx.datasets)})")
    return ctx.datasets[name]


def get_trial_info() -> Dict[str, Any]:
    return _require_ctx().trial_info
