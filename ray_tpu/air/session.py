"""Training session: the worker-side API inside a train loop.

Capability parity with the reference's ``session.report`` pipeline
(python/ray/air/session.py:12 → train/_internal/session.py:261): the user
loop calls ``report(metrics, checkpoint=)``; results stream to the trainer.
Also exposes rank/world/mesh context for SPMD loops.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint

_ctx = threading.local()


class TrainContext:
    def __init__(self, world_rank: int, world_size: int,
                 report_fn, mesh=None, trial_info: Optional[Dict] = None,
                 checkpoint: Optional[Checkpoint] = None,
                 config: Optional[Dict[str, Any]] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.report_fn = report_fn
        self.mesh = mesh
        self.trial_info = trial_info or {}
        self.loaded_checkpoint = checkpoint
        self.config = config or {}
        self.datasets = datasets or {}


def _require_ctx() -> TrainContext:
    ctx = getattr(_ctx, "ctx", None)
    if ctx is None:
        raise RuntimeError(
            "session API used outside a train loop (no active session)")
    return ctx


def in_session() -> bool:
    return getattr(_ctx, "ctx", None) is not None


def set_context(ctx: Optional[TrainContext]):
    _ctx.ctx = ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) to the trainer."""
    _require_ctx().report_fn(dict(metrics), checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from (set on restart), else None."""
    return _require_ctx().loaded_checkpoint


def get_world_rank() -> int:
    return _require_ctx().world_rank


def get_world_size() -> int:
    return _require_ctx().world_size


def get_mesh():
    """The jax device mesh built for this gang (None for CPU loops)."""
    return _require_ctx().mesh


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed to the trainer via
    ``datasets={name: ds}`` (reference: session.get_dataset_shard —
    equal-row shards, iterate with iter_batches /
    iter_torch_batches / iter_device_batches)."""
    ctx = _require_ctx()
    if name not in ctx.datasets:
        raise KeyError(
            f"no dataset {name!r} was passed to the trainer "
            f"(available: {sorted(ctx.datasets)})")
    return ctx.datasets[name]


def get_trial_info() -> Dict[str, Any]:
    return _require_ctx().trial_info
