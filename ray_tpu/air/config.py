"""Run/scaling/failure/checkpoint configs.

Capability parity with the reference's AIR configs (python/ray/air/config.py:
ScalingConfig/RunConfig/FailureConfig/CheckpointConfig). ScalingConfig grows
TPU-native fields: a MeshSpec and a slice topology instead of
use_gpu/num_gpus.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Union

from ray_tpu.mesh.device_mesh import MeshSpec


@dataclasses.dataclass
class ScalingConfig:
    """How a trainer scales.

    num_workers: host processes in the gang (1 per TPU VM host).
    chips_per_worker: TPU chips each host contributes (0 = CPU worker).
    mesh: logical mesh over the gang's chips (dict axis→size or MeshSpec);
          default = pure data parallel over all chips.
    topology: optional slice topology hint, e.g. "v5e-16", used by the
          distributed scheduler for ICI-aware placement.
    resources_per_worker: extra custom resources per worker.
    """
    num_workers: int = 1
    chips_per_worker: int = 0
    mesh: Optional[Union[MeshSpec, Dict[str, int]]] = None
    topology: Optional[str] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # Elastic floor: with a capacity oracle on the trainer, a restart
    # may proceed with as few as min_workers gang members when
    # preemption shrank capacity (data-parallel reshard), growing back
    # toward num_workers when capacity returns. None = num_workers
    # (non-elastic: a restart always waits for full capacity).
    min_workers: Optional[int] = None
    # Multi-host: bootstrap jax.distributed across the gang so the mesh
    # spans every member's devices. None = auto (on when num_workers>1
    # and the gang landed in distinct OS processes); True = require
    # (error if the runtime can't give the gang distinct processes);
    # False = never (each worker meshes only its local devices).
    jax_distributed: Optional[bool] = None

    def mesh_spec(self) -> Optional[MeshSpec]:
        if self.mesh is None:
            return None
        if isinstance(self.mesh, dict):
            return MeshSpec.from_dict(self.mesh)
        return self.mesh

    def worker_resources(self) -> Dict[str, float]:
        res: Dict[str, float] = {"CPU": 1.0}
        if self.chips_per_worker:
            res["TPU"] = float(self.chips_per_worker)
        if self.resources_per_worker:
            res.update(self.resources_per_worker)
        return res


@dataclasses.dataclass
class FailureConfig:
    """Gang fault-tolerance policy.

    max_failures: gang restarts before giving up (-1 = infinite). The
        budget counts consecutive failures WITHOUT durable progress: a
        failure arriving with a newer checkpoint than the previous
        failure's resets the count, so intermittent faults on a long
        run don't exhaust the budget despite real forward progress.
    worker_progress_deadline_s: heartbeat deadline — if a live worker
        reports no progress (no session.report / session.heartbeat)
        for this long, the gang is declared wedged and elastically
        restarted instead of stalling fit() forever. None disables.
    max_preemptions: preemption-driven restarts before giving up
        (-1 = infinite). Preemptions drain through a checkpoint and
        never consume the failure budget — capacity loss is not an
        application fault.
    """
    max_failures: int = 0
    worker_progress_deadline_s: Optional[float] = None
    max_preemptions: int = -1


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = True


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    verbose: int = 1
    # Stopping condition: a tune.Stopper, a {"metric": threshold}
    # dict, or a callable(trial_id, result) -> bool (reference:
    # air.RunConfig.stop -> tune/stopper/).
    stop: Optional[Any] = None
