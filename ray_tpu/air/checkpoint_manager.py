"""CheckpointManager: durable, asynchronous, self-pruning checkpoints.

The train-loop-facing layer over :mod:`ray_tpu.air.checkpoint`'s atomic
commit (reference analogue: train/_internal/checkpoint_manager.py +
orbax's AsyncCheckpointer, redesigned for gang preemption tolerance):

- ``save_async(data, step)`` snapshots the payload ON THE CALLING THREAD
  (host-memory copy only — jax.Arrays are immutable and numpy arrays are
  copied) and hands serialization + fsync + atomic rename to a single
  background writer thread, so a train step overlapping a save never
  blocks on checkpoint I/O and the committed bytes are exactly the
  values at the step the save was requested.
- commits land as ``step_<N>`` directories via write-to-temp + manifest
  + atomic rename; a crash at any instant leaves only ``.tmp-*`` litter
  that no resolver reads.
- keep-last-K retention prunes older COMMITTED checkpoints after each
  successful commit (torn/alien directories are never counted against
  the budget, never deleted — they are evidence).
- ``latest_complete()`` scans newest-first and returns the first
  directory that passes a deep manifest verification, skipping torn or
  corrupted ones — the resume resolver a preempted gang restarts from.
"""
from __future__ import annotations

import logging
import os
import queue
import re
import shutil
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ray_tpu.air.checkpoint import (Checkpoint, InvalidCheckpointError,
                                    verify_checkpoint_dir)

logger = logging.getLogger(__name__)

_STEP_DIR_RE = re.compile(r"^step_(\d{8})$")


def step_dir_name(step: int) -> str:
    return f"step_{step:08d}"


def _snapshot(data: Dict[str, Any]) -> Dict[str, Any]:
    """Host-side copy of the payload so later in-place mutation by the
    train loop cannot leak into an in-flight save. jax.Arrays are
    immutable; numpy buffers are copied; everything else is assumed
    value-like (config scalars, strings)."""
    def copy_leaf(x):
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        return x
    return {k: jax.tree_util.tree_map(copy_leaf, v)
            for k, v in data.items()}


class SaveHandle:
    """Tracks one async save. ``wait()`` blocks until the commit (or
    failure) of THIS save; ``committed`` / ``error`` afterwards."""

    def __init__(self, step: int):
        self.step = step
        self.committed = False
        self.error: Optional[BaseException] = None
        self.path: Optional[str] = None
        self._done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class CheckpointManager:
    """One training run's checkpoint directory tree.

    ``keep_last_k=None`` keeps everything. ``pre_commit_hook`` is a test
    seam called on the writer thread after staging but before the
    atomic rename becomes observable — chaos tests use it to hold a
    save in flight or simulate a crash-before-commit.
    """

    def __init__(self, root_dir: str, keep_last_k: Optional[int] = None,
                 pre_commit_hook: Optional[Callable[[int], None]] = None):
        if keep_last_k is not None and keep_last_k < 1:
            raise ValueError("keep_last_k must be >= 1 or None")
        self.root = os.path.abspath(root_dir)
        os.makedirs(self.root, exist_ok=True)
        self.keep_last_k = keep_last_k
        self._pre_commit_hook = pre_commit_hook
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._inflight = 0
        self._idle = threading.Event()
        self._idle.set()
        self._last_error: Optional[BaseException] = None
        self._closed = False
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="ckpt-writer", daemon=True)
        self._writer.start()

    # ------------------------------------------------------------- saves

    def save_async(self, data: Dict[str, Any], step: int) -> SaveHandle:
        """Snapshot ``data`` now; commit ``step_<step>`` in the
        background. Never blocks on disk."""
        if self._closed:
            raise RuntimeError("CheckpointManager is closed")
        handle = SaveHandle(step)
        snap = _snapshot(data)
        with self._lock:
            self._inflight += 1
            self._idle.clear()
        self._q.put((snap, step, handle))
        return handle

    def save(self, data: Dict[str, Any], step: int) -> SaveHandle:
        """Synchronous convenience: save_async + wait, raising on
        failure."""
        handle = self.save_async(data, step)
        handle.wait()
        if handle.error is not None:
            raise handle.error
        return handle

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every save enqueued so far has committed or
        failed. Raises the first writer error, if any."""
        if not self._idle.wait(timeout):
            raise TimeoutError(
                f"checkpoint writer still busy after {timeout}s")
        with self._lock:
            err, self._last_error = self._last_error, None
        if err is not None:
            raise err

    def close(self) -> None:
        """Flush pending saves and stop the writer thread."""
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._writer.join(timeout=60)

    def _writer_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            snap, step, handle = item
            try:
                path = os.path.join(self.root, step_dir_name(step))
                if self._pre_commit_hook is not None:
                    self._pre_commit_hook(step)
                handle.path = Checkpoint.from_dict(snap).to_directory(
                    path, step=step)
                handle.committed = True
                self._retain()
            except BaseException as e:  # noqa: BLE001
                handle.error = e
                with self._lock:
                    if self._last_error is None:
                        self._last_error = e
                logger.warning("async checkpoint save (step %d) failed: "
                               "%s", step, e)
            finally:
                handle._done.set()
                with self._lock:
                    self._inflight -= 1
                    if self._inflight == 0:
                        self._idle.set()

    # ---------------------------------------------------------- resolve

    def _scan(self) -> List[Tuple[int, str]]:
        """(step, path) of every ``step_*`` directory, ascending by
        step. Staging litter (``.tmp-*``) is invisible by name."""
        out = []
        try:
            entries = os.listdir(self.root)
        except OSError:
            return out
        for name in entries:
            m = _STEP_DIR_RE.match(name)
            full = os.path.join(self.root, name)
            if m and os.path.isdir(full):
                out.append((int(m.group(1)), full))
        out.sort()
        return out

    def steps(self, complete_only: bool = True) -> List[int]:
        """Committed checkpoint steps, ascending. With
        ``complete_only`` each candidate is (shallow-)verified."""
        out = []
        for step, path in self._scan():
            if not complete_only or verify_checkpoint_dir(path)[0]:
                out.append(step)
        return out

    def latest_complete(self) -> Optional[Checkpoint]:
        """Newest checkpoint that passes DEEP verification (every file
        present, sized, and hash-matching its manifest). Torn or
        corrupted directories are skipped with a warning — resume must
        never load them — and the next-older complete one wins."""
        for step, path in reversed(self._scan()):
            ok, reason, _manifest = verify_checkpoint_dir(path, deep=True)
            if ok:
                return Checkpoint.from_directory(path)
            logger.warning("skipping torn checkpoint %s: %s", path,
                           reason)
        return None

    def latest_step(self) -> Optional[int]:
        """Step of :meth:`latest_complete`'s winner (manifest-recorded),
        None when no complete checkpoint exists."""
        for step, path in reversed(self._scan()):
            ok, _reason, manifest = verify_checkpoint_dir(path, deep=True)
            if ok:
                mstep = manifest.get("step")
                return mstep if isinstance(mstep, int) else step
        return None

    # --------------------------------------------------------- retention

    def _retain(self) -> None:
        if self.keep_last_k is None:
            return
        # Deep verification before deletion: a torn directory can pass
        # the shallow (size-only) check, and pruning one would destroy
        # the evidence of the corruption it records.
        complete = [(s, p) for s, p in self._scan()
                    if verify_checkpoint_dir(p, deep=True)[0]]
        for _step, path in complete[:-self.keep_last_k]:
            shutil.rmtree(path, ignore_errors=True)
