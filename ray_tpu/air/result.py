"""Result of a training/tuning run (reference: python/ray/air/result.py)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Checkpoint]
    error: Optional[BaseException] = None
    metrics_history: Optional[List[Dict[str, Any]]] = None

    @property
    def ok(self) -> bool:
        return self.error is None
