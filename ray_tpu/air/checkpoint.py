"""Checkpoint: the canonical training artifact.

Capability parity with the reference's AIR Checkpoint
(python/ray/air/checkpoint.py:42 — dict ↔ directory ↔ URI interconversion,
passed between workers/trainables/driver). TPU-native twist: array pytrees
(including sharded `jax.Array`s) are persisted via orbax — the
distributed-checkpoint path that makes gang restarts cheap (SURVEY.md §7
hard part 6); non-array metadata rides alongside as a pickle.
"""
from __future__ import annotations

import os
import shutil
import tempfile
from typing import Any, Dict, Optional

import jax
import numpy as np

from ray_tpu._private import serialization

_ARRAY_SUBDIR = "arrays"
_META_FILE = "meta.pkl"


def _is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


def _split(data: Dict[str, Any]):
    """Split a checkpoint dict into (array-pytree entries, other entries).
    An entry goes to orbax iff every leaf of its value is an array."""
    arrays, other = {}, {}
    for k, v in data.items():
        leaves = jax.tree_util.tree_leaves(v)
        if leaves and all(_is_array(l) for l in leaves):
            arrays[k] = v
        else:
            other[k] = v
    return arrays, other


class Checkpoint:
    """Immutable checkpoint; create via ``from_dict``/``from_directory``."""

    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 path: Optional[str] = None):
        if (data is None) == (path is None):
            raise ValueError("Provide exactly one of data / path")
        self._data = data
        self._path = path

    # --- constructors -----------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise FileNotFoundError(path)
        return cls(path=path)

    # --- conversions ------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        meta_path = os.path.join(self._path, _META_FILE)
        out: Dict[str, Any] = {}
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                out.update(serialization.loads(f.read()))
        arr_dir = os.path.join(self._path, _ARRAY_SUBDIR)
        if os.path.isdir(arr_dir):
            import orbax.checkpoint as ocp
            with ocp.PyTreeCheckpointer() as ckptr:
                restored = ckptr.restore(os.path.abspath(arr_dir))
            out.update(restored)
        return out

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        path = os.path.abspath(path)
        if self._path is not None:
            if os.path.abspath(self._path) != path:
                shutil.copytree(self._path, path, dirs_exist_ok=True)
            return path
        os.makedirs(path, exist_ok=True)
        arrays, other = _split(self._data)
        with open(os.path.join(path, _META_FILE), "wb") as f:
            f.write(serialization.dumps(other))
        if arrays:
            import orbax.checkpoint as ocp
            arr_dir = os.path.join(path, _ARRAY_SUBDIR)
            if os.path.exists(arr_dir):
                shutil.rmtree(arr_dir)
            with ocp.PyTreeCheckpointer() as ckptr:
                ckptr.save(arr_dir, arrays)
        return path

    # --- helpers ----------------------------------------------------------

    def __getitem__(self, key: str):
        return self.to_dict()[key]

    def get(self, key: str, default=None):
        return self.to_dict().get(key, default)

    def __contains__(self, key: str) -> bool:
        return key in self.to_dict()

    def __repr__(self):
        src = "dict" if self._data is not None else self._path
        return f"Checkpoint({src})"


def restore_sharded(path: str, target, mesh=None, rules=None):
    """Restore an array pytree with target shardings (for gang restarts:
    each host restores only its shards). `target` is a pytree of
    ShapeDtypeStructs or arrays giving shapes/dtypes; shardings from
    `rules` over `mesh` when given."""
    import orbax.checkpoint as ocp
    arr_dir = os.path.abspath(os.path.join(path, _ARRAY_SUBDIR))
    if rules is not None and mesh is not None:
        from ray_tpu.mesh.sharding import infer_sharding
        shardings = infer_sharding(target, rules, mesh)
        target = jax.tree_util.tree_map(
            lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype,
                                              sharding=s),
            target, shardings)
    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(arr_dir, ocp.args.PyTreeRestore(
            restore_args=ocp.checkpoint_utils.construct_restore_args(
                target)))
