"""Checkpoint: the canonical training artifact.

Capability parity with the reference's AIR Checkpoint
(python/ray/air/checkpoint.py:42 — dict ↔ directory ↔ URI interconversion,
passed between workers/trainables/driver). TPU-native twist: array pytrees
(including sharded `jax.Array`s) are persisted via orbax — the
distributed-checkpoint path that makes gang restarts cheap (SURVEY.md §7
hard part 6); non-array metadata rides alongside as a pickle.

Durability contract (the preemption-tolerance substrate): a checkpoint
directory is NEVER observable half-written. ``to_directory`` stages the
full payload in a sibling temp directory, fsyncs every file, writes a
content manifest (per-file SHA-256 + byte counts + step + wall time)
LAST, and commits with one atomic ``os.rename``. A reader therefore sees
either nothing or a complete, self-describing checkpoint; anything else
(a crash mid-write, a preempted host, a torn copy) leaves only a
``.tmp-*`` directory that every resolver ignores. ``from_directory``
refuses directories without a valid manifest with a typed
:class:`InvalidCheckpointError` so torn state can never flow back into a
resuming gang.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ray_tpu._private import serialization

_ARRAY_SUBDIR = "arrays"
_META_FILE = "meta.pkl"
MANIFEST_FILE = "manifest.json"
MANIFEST_FORMAT = 1
_TMP_PREFIX = ".tmp-"


class InvalidCheckpointError(RuntimeError):
    """The directory is not a complete committed checkpoint: missing,
    unparseable, or inconsistent manifest, or files that disagree with
    it (torn write / partial copy / bit rot)."""

    def __init__(self, path: str, reason: str):
        self.path = path
        self.reason = reason
        super().__init__(f"invalid checkpoint at {path}: {reason}")


def _is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


def _split(data: Dict[str, Any]):
    """Split a checkpoint dict into (array-pytree entries, other entries).
    An entry goes to orbax iff every leaf of its value is an array."""
    arrays, other = {}, {}
    for k, v in data.items():
        leaves = jax.tree_util.tree_leaves(v)
        if leaves and all(_is_array(l) for l in leaves):
            arrays[k] = v
        else:
            other[k] = v
    return arrays, other


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _payload_files(root: str) -> List[str]:
    """Every regular file under ``root`` except the manifest itself,
    as sorted relative paths."""
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root)
            if rel != MANIFEST_FILE:
                out.append(rel)
    return sorted(out)


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_manifest(root: str, step: Optional[int] = None) -> Dict[str, Any]:
    """Hash every payload file under ``root`` and write the manifest
    (fsynced). The manifest is written LAST so its presence implies the
    payload preceded it onto disk."""
    files = {}
    for rel in _payload_files(root):
        full = os.path.join(root, rel)
        files[rel] = {"sha256": _sha256(full),
                      "bytes": os.path.getsize(full)}
        _fsync_file(full)
    manifest = {"format": MANIFEST_FORMAT, "step": step,
                "wall_time": time.time(), "files": files}
    mpath = os.path.join(root, MANIFEST_FILE)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(root)
    return manifest


def load_manifest(path: str) -> Dict[str, Any]:
    """Read and structurally validate the manifest of a committed
    checkpoint directory. Raises :class:`InvalidCheckpointError`."""
    mpath = os.path.join(path, MANIFEST_FILE)
    if not os.path.isfile(mpath):
        raise InvalidCheckpointError(path, "missing manifest (torn or "
                                     "pre-manifest checkpoint)")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise InvalidCheckpointError(path, f"unreadable manifest: {e}")
    if not isinstance(manifest, dict) or \
            not isinstance(manifest.get("files"), dict) or \
            manifest.get("format") != MANIFEST_FORMAT:
        raise InvalidCheckpointError(path, "malformed manifest")
    return manifest


def verify_checkpoint_dir(path: str, deep: bool = False
                          ) -> Tuple[bool, Optional[str],
                                     Optional[Dict[str, Any]]]:
    """Is ``path`` a complete committed checkpoint? Shallow mode checks
    the manifest parses and every listed file exists with the recorded
    byte count; ``deep`` re-hashes contents (catches silent corruption,
    not just truncation). Returns (ok, reason_if_not, parsed_manifest)
    — the manifest rides along so callers that need ``step`` or the
    file table never re-read ``manifest.json`` after verifying."""
    try:
        manifest = load_manifest(path)
    except InvalidCheckpointError as e:
        return False, e.reason, None
    for rel, rec in manifest["files"].items():
        full = os.path.join(path, rel)
        if not os.path.isfile(full):
            return False, f"manifest lists missing file {rel!r}", manifest
        if os.path.getsize(full) != rec.get("bytes"):
            return False, (f"file {rel!r} is {os.path.getsize(full)}B, "
                           f"manifest says {rec.get('bytes')}B"), manifest
        if deep and _sha256(full) != rec.get("sha256"):
            return False, f"file {rel!r} fails its manifest hash", manifest
    # Extra payload files not in the manifest mean the directory was
    # tampered with after commit; tolerate (orbax may leave lockfiles)
    # but a missing/short file above is always fatal.
    return True, None, manifest


class Checkpoint:
    """Immutable checkpoint; create via ``from_dict``/``from_directory``."""

    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 path: Optional[str] = None):
        if (data is None) == (path is None):
            raise ValueError("Provide exactly one of data / path")
        self._data = data
        self._path = path

    # --- constructors -----------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise FileNotFoundError(path)
        ok, reason, _manifest = verify_checkpoint_dir(path)
        if not ok:
            raise InvalidCheckpointError(path, reason)
        return cls(path=path)

    # --- conversions ------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        meta_path = os.path.join(self._path, _META_FILE)
        out: Dict[str, Any] = {}
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                out.update(serialization.loads(f.read()))
        arr_dir = os.path.join(self._path, _ARRAY_SUBDIR)
        if os.path.isdir(arr_dir):
            import orbax.checkpoint as ocp
            with ocp.PyTreeCheckpointer() as ckptr:
                restored = ckptr.restore(os.path.abspath(arr_dir))
            out.update(restored)
        return out

    def to_directory(self, path: Optional[str] = None,
                     step: Optional[int] = None) -> str:
        """Materialize as a directory via stage → fsync → manifest →
        atomic rename. ``step`` is recorded in the manifest (falls back
        to an integer ``data['step']`` when present) so resolvers can
        order checkpoints without deserializing payloads."""
        if path is None:
            path = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
            # mkdtemp created the target itself; commit must swap it.
        path = os.path.abspath(path)
        if self._path is not None and os.path.abspath(self._path) == path:
            return path
        if step is None and self._data is not None:
            maybe = self._data.get("step")
            if isinstance(maybe, int) and not isinstance(maybe, bool):
                step = maybe
        parent = os.path.dirname(path) or "."
        os.makedirs(parent, exist_ok=True)
        stage = os.path.join(
            parent, f"{_TMP_PREFIX}{os.path.basename(path)}-"
                    f"{uuid.uuid4().hex[:8]}")
        try:
            if self._path is not None:
                shutil.copytree(self._path, stage)
                # Re-manifest: hashes re-verify the copy, and a torn
                # copy can never masquerade as the committed source.
                old = os.path.join(stage, MANIFEST_FILE)
                if step is None and os.path.isfile(old):
                    try:
                        with open(old) as f:
                            step = json.load(f).get("step")
                    except (OSError, json.JSONDecodeError):
                        step = None
                if os.path.exists(old):
                    os.remove(old)
            else:
                os.makedirs(stage)
                arrays, other = _split(self._data)
                with open(os.path.join(stage, _META_FILE), "wb") as f:
                    f.write(serialization.dumps(other))
                    f.flush()
                    os.fsync(f.fileno())
                if arrays:
                    import orbax.checkpoint as ocp
                    arr_dir = os.path.join(stage, _ARRAY_SUBDIR)
                    with ocp.PyTreeCheckpointer() as ckptr:
                        ckptr.save(arr_dir, arrays)
            write_manifest(stage, step=step)
            _commit_dir(stage, path)
        finally:
            if os.path.isdir(stage):
                shutil.rmtree(stage, ignore_errors=True)
        return path

    # --- helpers ----------------------------------------------------------

    def __getitem__(self, key: str):
        return self.to_dict()[key]

    def get(self, key: str, default=None):
        return self.to_dict().get(key, default)

    def __contains__(self, key: str) -> bool:
        return key in self.to_dict()

    def __repr__(self):
        src = "dict" if self._data is not None else self._path
        return f"Checkpoint({src})"


def _commit_dir(stage: str, path: str) -> None:
    """Atomically install ``stage`` at ``path``. A pre-existing target
    (re-save over an old checkpoint, or mkdtemp's empty dir) is swapped
    out first and removed after — at every instant ``path`` is either
    the old complete state or the new one."""
    parent = os.path.dirname(path) or "."
    displaced = None
    if os.path.exists(path):
        displaced = os.path.join(
            parent, f"{_TMP_PREFIX}displaced-{uuid.uuid4().hex[:8]}")
        os.rename(path, displaced)
    try:
        os.rename(stage, path)
    except OSError:
        if displaced is not None:
            os.rename(displaced, path)     # roll back
        raise
    _fsync_dir(parent)
    if displaced is not None:
        shutil.rmtree(displaced, ignore_errors=True)


def restore_sharded(path: str, target, mesh=None, rules=None):
    """Restore an array pytree with target shardings (for gang restarts:
    each host restores only its shards). `target` is a pytree of
    ShapeDtypeStructs or arrays giving shapes/dtypes; shardings from
    `rules` over `mesh` when given. Because shardings are supplied by
    the RESTORING gang, the same checkpoint reshards onto a smaller or
    larger mesh — the elastic-resume path after a preemption shrank the
    slice."""
    import orbax.checkpoint as ocp
    arr_dir = os.path.abspath(os.path.join(path, _ARRAY_SUBDIR))
    if rules is not None and mesh is not None:
        from ray_tpu.mesh.sharding import infer_sharding
        shardings = infer_sharding(target, rules, mesh)
        target = jax.tree_util.tree_map(
            lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype,
                                              sharding=s),
            target, shardings)
    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(arr_dir, ocp.args.PyTreeRestore(
            restore_args=ocp.checkpoint_utils.construct_restore_args(
                target)))
