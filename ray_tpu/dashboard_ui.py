"""Single-file dashboard frontend (reference role: the dashboard's
React client, dashboard/client/ — here a dependency-free HTML page the
dashboard serves at "/", polling its own JSON endpoints). Stat tiles
for the headline numbers, tables for workers/actors/tasks/objects;
status is never color-alone (label + dot); light/dark via
prefers-color-scheme."""

INDEX_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
:root {
  --bg: #fafaf8; --surface: #ffffff; --ink: #1a1a1a;
  --ink-2: #555550; --ink-3: #8a8a84; --line: #e4e4df;
  --good: #1a7f37; --bad: #b42318; --warn: #9a6700;
  --accent: #4a64d0;
}
@media (prefers-color-scheme: dark) {
  :root {
    --bg: #16161a; --surface: #1f1f24; --ink: #ececec;
    --ink-2: #b0b0aa; --ink-3: #7c7c76; --line: #33333a;
    --good: #4ade80; --bad: #f87171; --warn: #fbbf24;
    --accent: #93a5f5;
  }
}
* { box-sizing: border-box; }
body { margin: 0; background: var(--bg); color: var(--ink);
       font: 14px/1.45 system-ui, sans-serif; }
header { padding: 14px 20px; border-bottom: 1px solid var(--line);
         display: flex; align-items: baseline; gap: 12px; }
header h1 { font-size: 16px; margin: 0; font-weight: 650; }
header .sub { color: var(--ink-3); font-size: 12px; }
main { max-width: 1100px; margin: 0 auto; padding: 16px 20px 40px; }
.tiles { display: grid; gap: 10px;
         grid-template-columns: repeat(auto-fit, minmax(150px, 1fr)); }
.tile { background: var(--surface); border: 1px solid var(--line);
        border-radius: 8px; padding: 12px 14px; }
.tile .label { font-size: 11px; text-transform: uppercase;
               letter-spacing: .04em; color: var(--ink-3); }
.tile .value { font-size: 24px; font-weight: 650; margin-top: 2px;
               font-variant-numeric: tabular-nums; }
.tile .hint { font-size: 11px; color: var(--ink-2); margin-top: 2px; }
h2 { font-size: 13px; text-transform: uppercase; letter-spacing: .05em;
     color: var(--ink-2); margin: 22px 0 8px; }
table { width: 100%; border-collapse: collapse;
        background: var(--surface); border: 1px solid var(--line);
        border-radius: 8px; overflow: hidden; font-size: 13px; }
th, td { text-align: left; padding: 6px 12px;
         border-bottom: 1px solid var(--line);
         font-variant-numeric: tabular-nums; }
th { font-size: 11px; text-transform: uppercase; color: var(--ink-3);
     letter-spacing: .04em; font-weight: 600; }
tr:last-child td { border-bottom: none; }
td.mono { font-family: ui-monospace, monospace; font-size: 12px;
          color: var(--ink-2); }
.pill { display: inline-flex; align-items: center; gap: 5px; }
.dot { width: 7px; height: 7px; border-radius: 50%; flex: none; }
.ok .dot { background: var(--good); } .ok { color: var(--good); }
.bad .dot { background: var(--bad); } .bad { color: var(--bad); }
.warn .dot { background: var(--warn); } .warn { color: var(--warn); }
.muted { color: var(--ink-3); }
.empty { color: var(--ink-3); padding: 10px 12px; }
#err { color: var(--bad); font-size: 12px; display: none; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="sub">cluster dashboard · refreshes every 2s</span>
  <span id="err">head unreachable</span>
</header>
<main>
  <div class="tiles" id="tiles"></div>
  <h2>Serve</h2><div id="serve"></div>
  <h2>Nodes</h2><div id="nodes"></div>
  <h2>Workers</h2><div id="workers"></div>
  <h2>Actors</h2><div id="actors"></div>
  <h2>Tasks</h2><div id="tasks"></div>
  <h2>Objects</h2><div id="objects"></div>
</main>
<script>
"use strict";
// all user-controlled strings pass through esc() before innerHTML
const esc = (s) => String(s).replace(/[&<>"']/g, (c) => ({
  "&": "&amp;", "<": "&lt;", ">": "&gt;",
  '"': "&quot;", "'": "&#39;"}[c]));
const fmt = (v) => typeof v === "number"
  ? (Number.isInteger(v) ? v.toLocaleString()
     : v.toLocaleString(undefined, {maximumFractionDigits: 2}))
  : String(v);
const gb = (b) => (b / 2 ** 30).toFixed(1) + " GB";

function tile(label, value, hint) {
  return `<div class="tile"><div class="label">${esc(label)}</div>` +
         `<div class="value">${esc(value)}</div>` +
         (hint ? `<div class="hint">${esc(hint)}</div>` : "") +
         `</div>`;
}
function pill(ok, text, warn) {
  const cls = ok ? "ok" : (warn ? "warn" : "bad");
  return `<span class="pill ${cls}"><span class="dot"></span>` +
         `${esc(text)}</span>`;
}
function table(rows, cols) {
  if (!rows.length) return `<div class="empty">none</div>`;
  const head = cols.map(c => `<th>${esc(c.label)}</th>`).join("");
  const body = rows.map(r =>
    `<tr>${cols.map(c => `<td class="${c.cls || ""}">` +
                         `${c.fn(r)}</td>`).join("")}</tr>`).join("");
  return `<table><thead><tr>${head}</tr></thead>` +
         `<tbody>${body}</tbody></table>`;
}
async function j(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(path);
  return r.json();
}
function resPair(total, avail, key) {
  const t = total[key] || 0, a = avail[key] ?? t;
  return `${fmt(t - a)} / ${fmt(t)} used`;
}
async function refresh() {
  try {
    const [sum, workers, actors, tasks, objects, nodes, srv] =
      await Promise.all([
      j("/api/cluster_summary"), j("/api/workers"), j("/api/actors"),
      j("/api/tasks"), j("/api/objects"),
      j("/api/nodes").catch(() => []),
      j("/api/serve").catch(() => ({deployments: {}}))]);
    const t = sum.resources_total || {}, a = sum.resources_available || {};
    const running = (sum.tasks || {}).RUNNING || 0;
    const finished = (sum.tasks || {}).FINISHED || 0;
    document.getElementById("tiles").innerHTML =
      tile("Workers", sum.workers ?? workers.length) +
      tile("CPU", fmt(t.CPU || 0), resPair(t, a, "CPU")) +
      (t.TPU ? tile("TPU chips", fmt(t.TPU), resPair(t, a, "TPU")) : "") +
      tile("Memory", gb(t.memory || 0), resPair(t, a, "memory")) +
      tile("Tasks running", running, `${fmt(finished)} finished`) +
      tile("Actors", Object.values(sum.actors || {})
                     .reduce((x, y) => x + y, 0));
    // serve deployments: status, replicas, in-flight, and any
    // serve_stats() user metrics (e.g. LLM engine slot occupancy)
    const deps = Object.entries(srv.deployments || {}).map(
      ([name, d]) => ({name, ...d}));
    document.getElementById("serve").innerHTML = deps.length
      ? table(deps, [
        {label: "deployment", fn: r => esc(r.name)},
        {label: "status", fn: r => pill(r.status === "HEALTHY",
                                        esc(r.status))},
        {label: "replicas", fn: r =>
          `${fmt(r.num_replicas)} / ${fmt(r.target ?? r.num_replicas)}`},
        {label: "in flight", fn: r => fmt((r.replica_stats || [])
          .reduce((x, s) => x + (s.ongoing || 0), 0))},
        {label: "served", fn: r => fmt((r.replica_stats || [])
          .reduce((x, s) => x + (s.total || 0), 0))},
        {label: "engine", fn: r => {
          // aggregate across replicas; values are user-controlled
          // (serve_stats hook) so they pass through esc() like
          // every other column
          const gs = (r.replica_stats || [])
            .map(s => (s.user || {}).engine).filter(g => g);
          if (!gs.length) return `<span class=muted>—</span>`;
          const sum = k => gs.reduce((x, g) => x + (+g[k] || 0), 0);
          return esc(`${fmt(sum("slots_live"))}/` +
                     `${fmt(sum("slots_total"))} slots, ` +
                     `${fmt(sum("completed"))} done`);
        }}])
      : `<span class=muted>no deployments</span>`;
    // per-node hardware rows (reporter_agent parity): cpu/mem/store
    // snapshots shipped with node heartbeats
    document.getElementById("nodes").innerHTML = table(nodes, [
      {label: "node", cls: "mono", fn: r => esc(r.node_id)},
      {label: "state", fn: r => pill(r.alive, r.alive ? "alive" : "dead")},
      {label: "cpu %", fn: r => r.hw ? fmt(r.hw.cpu_percent)
                               : `<span class=muted>—</span>`},
      {label: "load", fn: r => r.hw && r.hw.load_avg
                       ? fmt(r.hw.load_avg[0]) : `<span class=muted>—</span>`},
      {label: "mem", fn: r => r.hw && r.hw.mem
                       ? `${fmt(r.hw.mem.percent)}% of ${gb(r.hw.mem.total)}`
                       : `<span class=muted>—</span>`},
      {label: "object store", fn: r => {
        const s = r.hw && r.hw.object_store;
        return s ? `${gb(s.bytes_in_use)} / ${gb(s.capacity)}`
                 : `<span class=muted>—</span>`;
      }},
      {label: "tpu HBM", fn: r => {
        const t = r.hw && r.hw.tpu && r.hw.tpu[0];
        return t && t.hbm_bytes_in_use != null
          ? `${gb(t.hbm_bytes_in_use)} / ${gb(t.hbm_bytes_limit)}`
          : `<span class=muted>—</span>`;
      }}]);
    document.getElementById("workers").innerHTML = table(workers, [
      {label: "id", cls: "mono", fn: r => esc(r.worker_id)},
      {label: "state", fn: r => pill(r.alive, r.alive ? "alive" : "dead")},
      {label: "cpu", fn: r => resPair(r.resources || {},
                                      r.available || {}, "CPU")},
      {label: "node", cls: "mono",
       fn: r => esc(r.node_id || "head")}]);
    document.getElementById("actors").innerHTML = table(actors, [
      {label: "id", cls: "mono",
       fn: r => esc((r.actor_id || "").slice(0, 16))},
      {label: "class", fn: r => esc(r.class_name || r.name || "")},
      {label: "state", fn: r => {
        const s = r.state || (r.dead ? "DEAD" : "ALIVE");
        return pill(s === "ALIVE", s, s === "RESTARTING");
      }},
      {label: "name", fn: r => r.name ? esc(r.name)
                               : `<span class=muted>—</span>`}]);
    const recent = tasks.slice(-50).reverse();
    document.getElementById("tasks").innerHTML = table(recent, [
      {label: "task", fn: r => esc(r.name || "")},
      {label: "id", cls: "mono",
       fn: r => esc((r.task_id || "").slice(0, 16))},
      {label: "state", fn: r => {
        const s = r.state || "";
        return pill(s === "FINISHED" || s === "RUNNING", s,
                    s === "PENDING");
      }}]);
    document.getElementById("objects").innerHTML = table(
      objects.slice(0, 50), [
      {label: "object", cls: "mono",
       fn: r => esc((r.object_id || "").slice(0, 20))},
      {label: "refs", fn: r => fmt(r.ref_count ?? 0)},
      {label: "state", fn: r => pill(!!r.ready,
                                     r.ready ? "ready" : "pending",
                                     !r.ready)}]);
    document.getElementById("err").style.display = "none";
  } catch (e) {
    document.getElementById("err").style.display = "inline";
  }
}
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
