"""Runtime context (reference: ray.get_runtime_context /
runtime_context.RuntimeContext): identity of the current execution
site — job, worker, node, current task/actor — queryable from drivers,
tasks and actor methods alike.
"""
from __future__ import annotations

from typing import Optional


class RuntimeContext:
    """Snapshot accessor; construct via get_runtime_context()."""

    def get_job_id(self) -> Optional[str]:
        """The SUBMITTING job's id. Inside a task this derives from
        the current task id (TaskIDs embed their job's 4-byte prefix,
        _private/ids.py), so workers report the driver's job, not
        their own process-local one."""
        tid = self.get_task_id()
        if tid:
            return tid[:8]
        from ray_tpu._private.worker import global_worker
        rt = global_worker().runtime
        jid = getattr(rt, "job_id", None)
        return jid.hex() if jid is not None else None

    def get_worker_id(self) -> Optional[str]:
        from ray_tpu._private.worker import global_worker
        rt = global_worker().runtime
        return getattr(rt, "worker_id", None) or "driver"

    def get_node_id(self) -> Optional[str]:
        from ray_tpu._private.worker import global_worker
        rt = global_worker().runtime
        plane = getattr(rt, "plane", None) or getattr(
            getattr(rt, "_ex", None), "plane", None)
        return getattr(plane, "node_id", None) or "local"

    def get_task_id(self) -> Optional[str]:
        """Hex id of the task currently executing on THIS thread
        (None on the driver / outside task execution)."""
        # multiprocess executor threads (worker_main runs as
        # __main__, so the context lives in a neutral module)
        try:
            from ray_tpu._private.execution_context import task_ctx
            tid = getattr(task_ctx, "task_id", None)
            if tid:
                return tid
        except Exception:
            pass
        # local runtime
        try:
            from ray_tpu._private.local_runtime import \
                current_task_context
            ctx = current_task_context()
            if ctx is not None:
                return ctx.spec.task_id.hex()
        except Exception:
            pass
        return None

    def get_actor_id(self) -> Optional[str]:
        try:
            from ray_tpu._private.execution_context import task_ctx
            aid = getattr(task_ctx, "actor_id", None)
            if aid:
                return aid
        except Exception:
            pass
        # local runtime: the executing spec carries the actor id
        try:
            from ray_tpu._private.local_runtime import \
                current_task_context
            ctx = current_task_context()
            aid = getattr(ctx.spec, "actor_id", None) \
                if ctx is not None else None
            return aid.hex() if aid is not None else None
        except Exception:
            return None

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False      # restart counters live on the head


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()
