"""Multi-agent RL: MultiAgentEnv + per-policy independent PPO.

Capability parity with the reference's multi-agent support
(rllib/env/multi_agent_env.py — dict-keyed obs/action/reward per
agent; rllib/algorithms/algorithm_config.multi_agent() — a policies
dict and a policy_mapping_fn routing agents to policies). Training is
independent PPO per policy (the reference's default for parameter-
unshared policies): each policy has its own params/optimizer and
learns from exactly the transitions its agents generated; updates are
the same jitted learner as single-agent PPO, batched per policy.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import ENV_REGISTRY, CartPoleEnv


class MultiAgentEnv:
    """Dict-keyed multi-agent episode interface
    (rllib/env/multi_agent_env.py): reset/step return per-agent
    dicts; "__all__" in dones ends the episode."""

    agent_ids: List[str] = []
    observation_dim: int = 0
    num_actions: int = 0

    def reset(self, seed=None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, int]
             ) -> Tuple[Dict, Dict, Dict, Dict]:
        raise NotImplementedError


class MultiCartPole(MultiAgentEnv):
    """N independent CartPoles with shared episode boundaries — the
    standard smoke-test multi-agent env (each agent's transitions are
    its own; policies can be mapped per-agent or shared)."""

    def __init__(self, num_agents: int = 2, max_steps: int = 200):
        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {a: CartPoleEnv(max_steps=max_steps)
                      for a in self.agent_ids}
        probe = CartPoleEnv()
        self.observation_dim = probe.observation_dim
        self.num_actions = probe.num_actions
        self._done: Dict[str, bool] = {}

    def reset(self, seed=None) -> Dict[str, np.ndarray]:
        self._done = {a: False for a in self.agent_ids}
        return {a: e.reset(seed=None if seed is None else seed + i)
                for i, (a, e) in enumerate(self._envs.items())}

    def step(self, actions: Dict[str, int]):
        obs, rew, done = {}, {}, {}
        for a, act in actions.items():
            if self._done[a]:
                continue
            o, r, d, _ = self._envs[a].step(act)
            obs[a], rew[a], done[a] = o, r, d
            self._done[a] = d
        done["__all__"] = all(self._done.values())
        return obs, rew, done, {}


MULTI_ENV_REGISTRY: Dict[str, Callable[[], MultiAgentEnv]] = {
    "MultiCartPole": MultiCartPole,
}


class MultiAgentRolloutWorker:
    """Samples one multi-agent env with per-policy parameter sets;
    returns per-POLICY transition batches."""

    def __init__(self, env_name: str, hidden: int,
                 policy_ids: List[str], mapping: Dict[str, str],
                 seed: int):
        from ray_tpu.rllib.ppo import _policy_defs
        self.env = MULTI_ENV_REGISTRY[env_name]()
        self.mapping = mapping
        self._rng = np.random.RandomState(seed)
        self._model = _policy_defs(self.env.observation_dim,
                                   self.env.num_actions, hidden)
        self._params: Dict[str, Any] = {}
        self.obs = self.env.reset(seed=seed)
        self._ep_rewards: Dict[str, float] = \
            {a: 0.0 for a in self.env.agent_ids}
        self.completed: List[float] = []

    def set_weights(self, per_policy_params: Dict[str, Any]):
        self._params = per_policy_params

    def sample(self, num_steps: int) -> Dict[str, Dict[str, np.ndarray]]:
        import jax
        import jax.numpy as jnp
        apply = jax.jit(self._model.apply)
        bufs: Dict[str, Dict[str, list]] = {
            p: {k: [] for k in ("obs", "actions", "rewards", "dones",
                                "logp", "values")}
            for p in set(self.mapping.values())}
        for _ in range(num_steps):
            actions = {}
            step_info = {}
            for a, o in self.obs.items():
                pid = self.mapping[a]
                logits, value = apply(self._params[pid],
                                      jnp.asarray(o[None]))
                logits = np.asarray(logits[0], np.float64)
                probs = np.exp(logits - logits.max())
                probs /= probs.sum()
                act = int(self._rng.choice(len(probs), p=probs))
                actions[a] = act
                step_info[a] = (o, act,
                                float(np.log(probs[act] + 1e-12)),
                                float(value[0]))
            nobs, rew, done, _ = self.env.step(actions)
            for a, (o, act, logp, val) in step_info.items():
                pid = self.mapping[a]
                b = bufs[pid]
                b["obs"].append(o)
                b["actions"].append(act)
                b["rewards"].append(rew.get(a, 0.0))
                b["dones"].append(done.get(a, True))
                b["logp"].append(logp)
                b["values"].append(val)
                self._ep_rewards[a] += rew.get(a, 0.0)
            if done["__all__"]:
                self.completed.append(
                    sum(self._ep_rewards.values()))
                self._ep_rewards = {a: 0.0
                                    for a in self.env.agent_ids}
                self.obs = self.env.reset()
            else:
                # Done agents leave the episode: only agents the env
                # reported obs for keep acting (a finished agent must
                # not keep feeding frozen-obs transitions into its
                # policy's batch).
                self.obs = nobs
        out = {}
        for pid, b in bufs.items():
            if not b["actions"]:
                continue
            out[pid] = {
                "obs": np.asarray(b["obs"], np.float32),
                "actions": np.asarray(b["actions"], np.int32),
                "rewards": np.asarray(b["rewards"], np.float32),
                "dones": np.asarray(b["dones"], np.bool_),
                "logp": np.asarray(b["logp"], np.float32),
                "values": np.asarray(b["values"], np.float32),
                "last_value": 0.0,
            }
        return out

    def episode_rewards(self) -> List[float]:
        return self.completed[-100:]


@dataclasses.dataclass
class MultiAgentPPOConfig:
    env: str = "MultiCartPole"
    policies: Tuple[str, ...] = ("shared",)
    policy_mapping: Optional[Dict[str, str]] = None   # agent -> policy
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 128
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    lr: float = 3e-4
    num_sgd_epochs: int = 2
    minibatch_size: int = 64
    hidden_size: int = 64
    vf_coef: float = 0.5
    entropy_coef: float = 0.01
    seed: int = 0

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    def __init__(self, config: MultiAgentPPOConfig):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rllib.ppo import PPOConfig, PPO, _policy_defs
        self.config = config
        probe = MULTI_ENV_REGISTRY[config.env]()
        mapping = config.policy_mapping or {
            a: config.policies[i % len(config.policies)]
            for i, a in enumerate(probe.agent_ids)}
        self.mapping = mapping
        self.model = _policy_defs(probe.observation_dim,
                                  probe.num_actions,
                                  config.hidden_size)
        self.optimizer = optax.adam(config.lr)
        self.params: Dict[str, Any] = {}
        self.opt_states: Dict[str, Any] = {}
        for i, pid in enumerate(config.policies):
            p = self.model.init(
                jax.random.PRNGKey(config.seed + i),
                jnp.zeros((1, probe.observation_dim)))
            self.params[pid] = p
            self.opt_states[pid] = self.optimizer.init(p)
        # Reuse single-agent PPO's jitted minibatch-scan learner: the
        # update is policy-agnostic (params in, params out).
        ppo_cfg = PPOConfig(
            env="CartPole", num_rollout_workers=0,
            gamma=config.gamma, gae_lambda=config.gae_lambda,
            clip_eps=config.clip_eps, lr=config.lr,
            num_sgd_epochs=config.num_sgd_epochs,
            minibatch_size=config.minibatch_size,
            hidden_size=config.hidden_size,
            vf_coef=config.vf_coef,
            entropy_coef=config.entropy_coef, seed=config.seed)
        shim = PPO.__new__(PPO)
        shim.config = ppo_cfg
        shim.model = self.model
        shim.optimizer = self.optimizer
        self._update = PPO._build_update(shim)
        self._iteration = 0
        worker_cls = ray_tpu.remote(MultiAgentRolloutWorker)
        self.workers = [
            worker_cls.options(num_cpus=0.5).remote(
                config.env, config.hidden_size,
                list(config.policies), mapping, config.seed + i)
            for i in range(config.num_rollout_workers)]

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        from ray_tpu.rllib.ppo import PPO
        cfg = self.config
        t0 = time.time()
        wref = ray_tpu.put(self.params)
        ray_tpu.get([w.set_weights.remote(wref)
                     for w in self.workers])
        per_worker = ray_tpu.get(
            [w.sample.remote(cfg.rollout_fragment_length)
             for w in self.workers])
        losses = {}
        for pid in cfg.policies:
            obs, act, logp, adv, ret = [], [], [], [], []
            for batches in per_worker:
                b = batches.get(pid)
                if b is None:
                    continue
                a, r = PPO._gae(b, cfg.gamma, cfg.gae_lambda)
                obs.append(b["obs"])
                act.append(b["actions"])
                logp.append(b["logp"])
                adv.append(a)
                ret.append(r)
            if not obs:
                continue
            advs = np.concatenate(adv)
            advs = (advs - advs.mean()) / (advs.std() + 1e-8)
            data = {"obs": np.concatenate(obs),
                    "actions": np.concatenate(act),
                    "logp": np.concatenate(logp),
                    "adv": advs,
                    "returns": np.concatenate(ret)}
            n = len(data["actions"])
            mbs = max(1, min(cfg.minibatch_size, n))
            n_mb = max(1, n // mbs)
            order = np.random.RandomState(
                cfg.seed + self._iteration).permutation(n)[:n_mb * mbs]
            stacked = {
                k: jnp.asarray(v[order].reshape(
                    (n_mb, mbs) + v.shape[1:]))
                for k, v in data.items()}
            reps = {k: jnp.concatenate([stacked[k]] *
                                       cfg.num_sgd_epochs)
                    for k in stacked}
            self.params[pid], self.opt_states[pid], loss = \
                self._update(self.params[pid], self.opt_states[pid],
                             reps)
            losses[pid] = float(loss)
        self._iteration += 1
        rewards = [r for w in ray_tpu.get(
            [w.episode_rewards.remote() for w in self.workers])
            for r in w]
        return {
            "training_iteration": self._iteration,
            "policy_loss": losses,
            "episode_reward_mean": float(np.mean(rewards))
            if rewards else float("nan"),
            "time_this_iter_s": time.time() - t0,
        }

    def stop(self):
        for w in self.workers:
            ray_tpu.kill(w)
