"""IMPALA: asynchronous actor-learner training with V-trace correction.

Capability parity with the reference's IMPALA
(rllib/algorithms/impala/impala.py:620 training_step — workers sample
continuously and asynchronously; the learner consumes batches without
waiting for all workers, correcting off-policyness with V-trace
[Espeholt et al. 2018]). Here: rollout-worker actors sample with their
(possibly stale) policy snapshot; the learner drains whatever batches
are ready each step (ray_tpu.wait), applies one jitted V-trace update
per batch, and pushes fresh weights back — the async pattern rides the
task/actor layer the same way the reference rides object refs.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.ppo import RolloutWorker, _policy_defs
from ray_tpu.rllib.env import ENV_REGISTRY


def vtrace_returns(values, last_value, rewards, dones, rhos, *,
                   gamma, rho_bar=1.0, c_bar=1.0):
    """V-trace targets via a reverse scan (Espeholt et al. '18, eq. 1):
    vs = V(s) + sum_k (gamma^k * prod(c) * delta_k).

    Module level so external learners (e.g. ray_tpu.rl) can apply the
    same off-policy correction to token-level batches. Returns
    ``(vs, pg_adv)``, both stop-gradiented.
    """
    import jax
    import jax.numpy as jnp

    discounts = gamma * (1.0 - dones.astype(jnp.float32))
    next_values = jnp.concatenate(
        [values[1:], jnp.array([last_value])])
    clipped_rho = jnp.minimum(rho_bar, rhos)
    clipped_c = jnp.minimum(c_bar, rhos)
    deltas = clipped_rho * (
        rewards + discounts * next_values - values)

    def body(acc, xs):
        delta, disc, c = xs
        acc = delta + disc * c * acc
        return acc, acc

    _, advs = jax.lax.scan(
        body, jnp.float32(0.0),
        (deltas, discounts, clipped_c), reverse=True)
    vs = values + advs
    next_vs = jnp.concatenate(
        [vs[1:], jnp.array([last_value])])
    pg_adv = clipped_rho * (
        rewards + discounts * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaConfig(AlgorithmConfig):
    def _defaults(self) -> Dict[str, Any]:
        return {
            "vtrace_clip_rho": 1.0,
            "vtrace_clip_c": 1.0,
            "vf_coef": 0.5,
            "entropy_coef": 0.01,
            "max_batches_per_step": 4,
            "rollout_fragment_length": 128,
        }

    def algo_class(self):
        return Impala


class Impala(Algorithm):
    def _setup(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        env = ENV_REGISTRY[cfg.env]()
        self._model = _policy_defs(env.observation_dim,
                                   env.num_actions, cfg.hidden_size)
        key = jax.random.PRNGKey(cfg.seed)
        self._params = self._model.init(
            key, jnp.zeros((1, env.observation_dim), jnp.float32))
        self._opt = optax.adam(cfg.lr)
        self._opt_state = self._opt.init(self._params)
        worker_cls = ray_tpu.remote(num_cpus=1)(RolloutWorker)
        self._workers = [
            worker_cls.remote(cfg.env, cfg.hidden_size, cfg.seed + i)
            for i in range(cfg.num_rollout_workers)]
        host = jax.device_get(self._params)
        ray_tpu.get([w.set_weights.remote(host) for w in self._workers])
        # Kick off the first round of async sampling; _inflight maps
        # sample-ref -> worker so completed workers are immediately
        # re-tasked (the reference's async request manager).
        self._inflight: Dict[Any, Any] = {
            w.sample.remote(cfg.rollout_fragment_length): w
            for w in self._workers}
        self._update = self._build_update()

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        model = self._model
        gamma = cfg.gamma
        rho_bar = cfg.vtrace_clip_rho
        c_bar = cfg.vtrace_clip_c

        def loss_fn(params, batch):
            logits, values = model.apply(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            rhos = jnp.exp(logp - batch["logp"])
            vs, pg_adv = vtrace_returns(
                jax.lax.stop_gradient(values), batch["last_value"],
                batch["rewards"], batch["dones"], rhos,
                gamma=gamma, rho_bar=rho_bar, c_bar=c_bar)
            pg_loss = -jnp.mean(logp * pg_adv)
            vf_loss = jnp.mean((values - vs) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
            return pg_loss + cfg.vf_coef * vf_loss - \
                cfg.entropy_coef * entropy

        @jax.jit
        def update(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = self._opt.update(grads, opt_state,
                                                  params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return update

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        t0 = time.time()
        losses: List[float] = []
        steps = 0
        consumed = 0
        host = None
        while consumed < cfg.max_batches_per_step and self._inflight:
            ready, _ = ray_tpu.wait(list(self._inflight),
                                    num_returns=1, timeout=30)
            if not ready:
                break
            ref = ready[0]
            worker = self._inflight.pop(ref)
            batch = ray_tpu.get(ref)
            jb = {k: jnp.asarray(v) for k, v in batch.items()}
            self._params, self._opt_state, loss = self._update(
                self._params, self._opt_state, jb)
            losses.append(float(loss))
            steps += len(batch["actions"])
            consumed += 1
            # Refresh the worker's policy and re-task it immediately.
            host = jax.device_get(self._params)
            worker.set_weights.remote(host)
            self._inflight[worker.sample.remote(
                cfg.rollout_fragment_length)] = worker
        rewards: List[float] = []
        for w in self._workers:
            rewards.extend(ray_tpu.get(w.episode_rewards.remote()))
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "num_env_steps_sampled": steps,
            "num_batches_consumed": consumed,
            "loss": float(np.mean(losses)) if losses else None,
            "time_this_iter_s": time.time() - t0,
        }

    def compute_action(self, obs) -> int:
        """Greedy action from the learner policy."""
        from ray_tpu.rllib.algorithm import greedy_action
        return greedy_action(self, obs)

    def get_state(self) -> Dict[str, Any]:
        import jax
        return {"params": jax.device_get(self._params)}

    def set_state(self, state: Dict[str, Any]) -> None:
        import jax
        self._params = state["params"]
        self._opt_state = self._opt.init(self._params)
        host = jax.device_get(self._params)
        ray_tpu.get([w.set_weights.remote(host) for w in self._workers])

    def stop(self):
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
