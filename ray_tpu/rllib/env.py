"""Builtin environments (gym-style API without the gym dependency).

The reference's RL stack samples from gym envs inside RolloutWorker actors
(rllib/evaluation/rollout_worker.py:124; algorithm learning tests use
CartPole — rllib/algorithms/*/tests). Same API shape here: reset() -> obs,
step(a) -> (obs, reward, done, info).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np


class Env:
    observation_dim: int
    num_actions: int

    def reset(self, seed=None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, Dict]:
        raise NotImplementedError


class CartPoleEnv(Env):
    """Classic cart-pole balancing (physics per the standard formulation:
    Barto, Sutton & Anderson 1983), 500-step cap like CartPole-v1."""

    observation_dim = 4
    num_actions = 2

    def __init__(self, max_steps: int = 500):
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.length = 0.5
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_limit = 12 * 2 * np.pi / 360
        self.x_limit = 2.4
        self.max_steps = max_steps
        self._rng = np.random.RandomState(0)
        self.state = None
        self.t = 0

    def reset(self, seed=None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self.state = self._rng.uniform(-0.05, 0.05, size=4)
        self.t = 0
        return self.state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costh, sinth = np.cos(theta), np.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot ** 2 * sinth) / \
            total_mass
        theta_acc = (self.gravity * sinth - costh * temp) / (
            self.length * (4.0 / 3.0 -
                           self.masspole * costh ** 2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costh / total_mass
        x += self.tau * x_dot
        x_dot += self.tau * x_acc
        theta += self.tau * theta_dot
        theta_dot += self.tau * theta_acc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.t += 1
        done = bool(abs(x) > self.x_limit or
                    abs(theta) > self.theta_limit or
                    self.t >= self.max_steps)
        return self.state.astype(np.float32), 1.0, done, {}


class SignEnv(Env):
    """Trivially learnable: observation is a scalar; action 1 iff obs > 0
    earns +1, else -1. Episodes of fixed length. Used to keep learning
    tests fast (the reference uses CartPole; SignEnv converges in a few
    hundred steps)."""

    observation_dim = 1
    num_actions = 2

    def __init__(self, episode_len: int = 16):
        self.episode_len = episode_len
        self._rng = np.random.RandomState(0)
        self.t = 0
        self.obs = None

    def reset(self, seed=None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self.t = 0
        self.obs = self._rng.randn(1).astype(np.float32)
        return self.obs

    def step(self, action: int):
        correct = (action == 1) == (float(self.obs[0]) > 0)
        reward = 1.0 if correct else -1.0
        self.t += 1
        self.obs = self._rng.randn(1).astype(np.float32)
        return self.obs, reward, self.t >= self.episode_len, {}


class ContinuousEnv(Env):
    """Continuous-action env contract: actions are float vectors of
    shape (action_dim,) clipped to [action_low, action_high]."""

    action_dim: int
    action_low: float
    action_high: float


class PendulumEnv(ContinuousEnv):
    """Classic inverted-pendulum swing-up (standard formulation used by
    Pendulum-v1): obs = [cos th, sin th, th_dot], action = torque in
    [-2, 2], reward = -(th^2 + 0.1 th_dot^2 + 0.001 a^2)."""

    observation_dim = 3
    num_actions = 0           # continuous: see action_dim
    action_dim = 1
    action_low = -2.0
    action_high = 2.0

    def __init__(self, max_steps: int = 200):
        self.max_speed = 8.0
        self.dt = 0.05
        self.g = 10.0
        self.m = 1.0
        self.length = 1.0
        self.max_steps = max_steps
        self._rng = np.random.RandomState(0)
        self.th = 0.0
        self.th_dot = 0.0
        self.t = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self.th), np.sin(self.th),
                         self.th_dot], np.float32)

    def reset(self, seed=None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self.th = self._rng.uniform(-np.pi, np.pi)
        self.th_dot = self._rng.uniform(-1.0, 1.0)
        self.t = 0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          self.action_low, self.action_high))
        th_norm = ((self.th + np.pi) % (2 * np.pi)) - np.pi
        cost = th_norm ** 2 + 0.1 * self.th_dot ** 2 + 0.001 * u ** 2
        self.th_dot += (3 * self.g / (2 * self.length) * np.sin(self.th)
                        + 3.0 / (self.m * self.length ** 2) * u) * self.dt
        self.th_dot = float(np.clip(self.th_dot, -self.max_speed,
                                    self.max_speed))
        self.th += self.th_dot * self.dt
        self.t += 1
        return self._obs(), -cost, self.t >= self.max_steps, {}


class ReachEnv(ContinuousEnv):
    """Trivially learnable continuous control (the SignEnv analogue for
    off-policy continuous learners): observation is a random target in
    [-1, 1]; reward = -(action - target)^2. Optimal policy copies the
    observation; converges in a few hundred steps."""

    observation_dim = 1
    num_actions = 0
    action_dim = 1
    action_low = -1.0
    action_high = 1.0

    def __init__(self, episode_len: int = 8):
        self.episode_len = episode_len
        self._rng = np.random.RandomState(0)
        self.t = 0
        self.obs = None

    def reset(self, seed=None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self.t = 0
        self.obs = self._rng.uniform(-1, 1, size=1).astype(np.float32)
        return self.obs

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1)[0], -1.0, 1.0))
        reward = -float((a - float(self.obs[0])) ** 2)
        self.t += 1
        self.obs = self._rng.uniform(-1, 1, size=1).astype(np.float32)
        return self.obs, reward, self.t >= self.episode_len, {}


ENV_REGISTRY = {
    "CartPole": CartPoleEnv,
    "Sign": SignEnv,
    "Pendulum": PendulumEnv,
    "Reach": ReachEnv,
}
