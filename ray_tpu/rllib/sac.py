"""SAC: off-policy continuous control with twin critics and entropy
maximization.

Capability parity with the reference's SAC family
(rllib/algorithms/sac/sac.py — replay-buffer training_step, twin
soft-Q critics with polyak-averaged targets, tanh-squashed Gaussian
policy, automatic temperature tuning against a target entropy of
-action_dim). The learner is one jitted update (critics + actor +
alpha in a single compiled step, TPU when present); rollout workers
are CPU actors sampling from the current stochastic policy.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.dqn import ReplayBuffer
from ray_tpu.rllib.env import ENV_REGISTRY

_LOG_STD_MIN, _LOG_STD_MAX = -10.0, 2.0


def _policy_net(action_dim: int, hidden: int):
    import flax.linen as nn

    class PolicyNet(nn.Module):
        @nn.compact
        def __call__(self, obs):
            h = nn.relu(nn.Dense(hidden)(obs))
            h = nn.relu(nn.Dense(hidden)(h))
            mu = nn.Dense(action_dim)(h)
            log_std = nn.Dense(action_dim)(h)
            return mu, log_std.clip(_LOG_STD_MIN, _LOG_STD_MAX)

    return PolicyNet()


def _twin_q_net(hidden: int):
    import flax.linen as nn
    import jax.numpy as jnp

    class TwinQ(nn.Module):
        @nn.compact
        def __call__(self, obs, action):
            x = jnp.concatenate([obs, action], axis=-1)
            qs = []
            for _ in range(2):
                h = nn.relu(nn.Dense(hidden)(x))
                h = nn.relu(nn.Dense(hidden)(h))
                qs.append(nn.Dense(1)(h)[..., 0])
            return qs[0], qs[1]

    return TwinQ()


def _squash(mu, log_std, eps, scale, center):
    """Tanh-squashed Gaussian sample and its log-prob (with the tanh
    change-of-variables correction), affinely mapped to the action
    range: a = center + tanh(pre) * scale."""
    import jax
    import jax.numpy as jnp

    std = jnp.exp(log_std)
    pre = mu + std * eps
    # Gaussian log-prob of the pre-squash sample.
    logp = (-0.5 * (eps ** 2) - log_std -
            0.5 * jnp.log(2 * jnp.pi)).sum(axis=-1)
    # log|d a/d pre| = log(1 - tanh(pre)^2) + log(scale), with the tanh
    # term in its stable form 2*(log 2 - pre - softplus(-2*pre)).
    logp -= (2 * (jnp.log(2.0) - pre - jax.nn.softplus(-2 * pre)) +
             jnp.log(scale)).sum(axis=-1)
    return jnp.tanh(pre) * scale + center, logp


class SACRolloutWorker:
    """CPU actor: samples from the current tanh-Gaussian policy."""

    def __init__(self, env_name: str, hidden: int, seed: int):
        self.env = ENV_REGISTRY[env_name]()
        self.obs = self.env.reset(seed=seed)
        self._rng = np.random.RandomState(seed)
        self._params = None
        self._model = _policy_net(self.env.action_dim, hidden)
        self._apply = None
        self._episode_reward = 0.0
        self.completed_rewards: List[float] = []

    def set_weights(self, params):
        self._params = params

    def sample(self, num_steps: int, random_actions: bool
               ) -> Dict[str, np.ndarray]:
        import jax
        import jax.numpy as jnp
        if self._apply is None:
            self._apply = jax.jit(self._model.apply)
        env = self.env
        scale = (env.action_high - env.action_low) / 2.0
        center = (env.action_high + env.action_low) / 2.0
        obs_b, nobs_b, act_b, rew_b, done_b = [], [], [], [], []
        for _ in range(num_steps):
            if random_actions:
                action = self._rng.uniform(
                    env.action_low, env.action_high,
                    size=env.action_dim).astype(np.float32)
            else:
                mu, log_std = self._apply(
                    self._params, jnp.asarray(self.obs[None]))
                mu = np.asarray(mu[0])
                std = np.exp(np.asarray(log_std[0]))
                pre = mu + std * self._rng.randn(env.action_dim)
                action = (np.tanh(pre) * scale + center).astype(
                    np.float32)
            next_obs, reward, done, _ = env.step(action)
            obs_b.append(self.obs)
            nobs_b.append(next_obs)
            act_b.append(action)
            rew_b.append(reward)
            done_b.append(done)
            self._episode_reward += reward
            if done:
                self.completed_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self.obs = env.reset()
            else:
                self.obs = next_obs
        return {"obs": np.asarray(obs_b, np.float32),
                "next_obs": np.asarray(nobs_b, np.float32),
                "actions": np.asarray(act_b, np.float32),
                "rewards": np.asarray(rew_b, np.float32),
                "dones": np.asarray(done_b, np.bool_)}

    def episode_rewards(self) -> List[float]:
        return list(self.completed_rewards[-100:])


class SACConfig(AlgorithmConfig):
    def _defaults(self) -> Dict[str, Any]:
        return {
            "replay_buffer_capacity": 50_000,
            "learning_starts": 256,
            "train_batch_size": 128,
            "num_sgd_iter_per_step": 16,
            "tau": 0.005,                 # polyak target-critic rate
            "initial_alpha": 0.1,
            "auto_alpha": True,           # tune temperature to -action_dim
            "rollout_fragment_length": 128,
        }

    def algo_class(self):
        return SAC


class SAC(Algorithm):
    def _setup(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        env = ENV_REGISTRY[cfg.env]()
        if getattr(env, "action_dim", 0) <= 0:
            raise ValueError(
                f"SAC needs a continuous-action env; {cfg.env!r} is "
                "discrete (use DQN/PPO, or a ContinuousEnv)")
        self._obs_dim = env.observation_dim
        self._action_dim = env.action_dim
        self._scale = float(env.action_high - env.action_low) / 2.0
        self._center = float(env.action_high + env.action_low) / 2.0
        self._policy = _policy_net(self._action_dim, cfg.hidden_size)
        self._critic = _twin_q_net(cfg.hidden_size)
        k0, k1, key = jax.random.split(jax.random.PRNGKey(cfg.seed), 3)
        zo = jnp.zeros((1, self._obs_dim), jnp.float32)
        za = jnp.zeros((1, self._action_dim), jnp.float32)
        self._state = {
            "pi": self._policy.init(k0, zo),
            "q": self._critic.init(k1, zo, za),
            "q_target": None,
            "log_alpha": jnp.asarray(
                np.log(cfg.initial_alpha), jnp.float32),
        }
        self._state["q_target"] = self._state["q"]
        self._opt = optax.adam(cfg.lr)
        self._opt_state = {
            "pi": self._opt.init(self._state["pi"]),
            "q": self._opt.init(self._state["q"]),
            "alpha": self._opt.init(self._state["log_alpha"]),
        }
        self._key = key
        self._rng = np.random.RandomState(cfg.seed)
        self._buffer = ReplayBuffer(
            cfg.replay_buffer_capacity, self._obs_dim,
            action_shape=(self._action_dim,), action_dtype=np.float32)
        worker_cls = ray_tpu.remote(num_cpus=1)(SACRolloutWorker)
        self._workers = [
            worker_cls.remote(cfg.env, cfg.hidden_size, cfg.seed + i)
            for i in range(cfg.num_rollout_workers)]
        self._sync_weights()
        self._update = self._build_update()

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        policy, critic = self._policy, self._critic
        gamma, tau = cfg.gamma, cfg.tau
        scale, center = self._scale, self._center
        target_entropy = -float(self._action_dim)
        auto_alpha = cfg.auto_alpha
        opt = self._opt

        def critic_loss(q_params, state, batch, key):
            mu, log_std = policy.apply(state["pi"], batch["next_obs"])
            eps = jax.random.normal(key, mu.shape)
            next_a, next_logp = _squash(mu, log_std, eps, scale, center)
            tq1, tq2 = critic.apply(state["q_target"],
                                    batch["next_obs"], next_a)
            alpha = jnp.exp(state["log_alpha"])
            next_v = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = batch["rewards"] + gamma * next_v * \
                (1.0 - batch["dones"].astype(jnp.float32))
            target = jax.lax.stop_gradient(target)
            q1, q2 = critic.apply(q_params, batch["obs"],
                                  batch["actions"])
            return jnp.mean((q1 - target) ** 2 + (q2 - target) ** 2)

        def actor_loss(pi_params, state, batch, key):
            mu, log_std = policy.apply(pi_params, batch["obs"])
            eps = jax.random.normal(key, mu.shape)
            a, logp = _squash(mu, log_std, eps, scale, center)
            q1, q2 = critic.apply(state["q"], batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(state["log_alpha"]))
            return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

        def alpha_loss(log_alpha, logp):
            ent_gap = jax.lax.stop_gradient(logp + target_entropy)
            return -jnp.mean(log_alpha * ent_gap)

        @jax.jit
        def update(state, opt_state, batch, key):
            kc, ka = jax.random.split(key)
            closs, q_grads = jax.value_and_grad(critic_loss)(
                state["q"], state, batch, kc)
            upd, opt_state_q = opt.update(
                q_grads, opt_state["q"], state["q"])
            state = dict(state, q=optax.apply_updates(state["q"], upd))
            (aloss, logp), pi_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(state["pi"], state, batch, ka)
            upd, opt_state_pi = opt.update(
                pi_grads, opt_state["pi"], state["pi"])
            state = dict(state,
                         pi=optax.apply_updates(state["pi"], upd))
            opt_state = dict(opt_state, q=opt_state_q, pi=opt_state_pi)
            if auto_alpha:
                al_grad = jax.grad(alpha_loss)(state["log_alpha"], logp)
                upd, opt_state_a = opt.update(
                    al_grad, opt_state["alpha"], state["log_alpha"])
                state = dict(state, log_alpha=optax.apply_updates(
                    state["log_alpha"], upd))
                opt_state = dict(opt_state, alpha=opt_state_a)
            state = dict(state, q_target=jax.tree_util.tree_map(
                lambda t, q: (1 - tau) * t + tau * q,
                state["q_target"], state["q"]))
            return state, opt_state, closs, aloss

        return update

    def _sync_weights(self):
        import jax
        host = jax.device_get(self._state["pi"])
        ray_tpu.get([w.set_weights.remote(host) for w in self._workers])

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        t0 = time.time()
        warmup = self._buffer.size < cfg.learning_starts
        batches = ray_tpu.get([
            w.sample.remote(cfg.rollout_fragment_length, warmup)
            for w in self._workers])
        for b in batches:
            self._buffer.add_batch(b)
        steps = sum(len(b["actions"]) for b in batches)
        closses, alosses = [], []
        if self._buffer.size >= cfg.learning_starts:
            for _ in range(cfg.num_sgd_iter_per_step):
                mb = self._buffer.sample(cfg.train_batch_size, self._rng)
                mb = {k: jnp.asarray(v) for k, v in mb.items()}
                self._key, sub = jax.random.split(self._key)
                self._state, self._opt_state, closs, aloss = \
                    self._update(self._state, self._opt_state, mb, sub)
                closses.append(float(closs))
                alosses.append(float(aloss))
            self._sync_weights()
        rewards: List[float] = []
        for w in self._workers:
            rewards.extend(ray_tpu.get(w.episode_rewards.remote()))
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "num_env_steps_sampled": steps,
            "buffer_size": self._buffer.size,
            "alpha": float(np.exp(float(self._state["log_alpha"]))),
            "critic_loss": float(np.mean(closses)) if closses else None,
            "actor_loss": float(np.mean(alosses)) if alosses else None,
            "time_this_iter_s": time.time() - t0,
        }

    def compute_action(self, obs, deterministic: bool = True
                       ) -> np.ndarray:
        """Action for one observation from the learned policy (the
        tanh-squashed mean when deterministic, a sample otherwise),
        mapped to the env's action range."""
        import jax
        import jax.numpy as jnp
        mu, log_std = self._policy.apply(self._state["pi"],
                                         jnp.asarray(obs)[None])
        mu = np.asarray(mu[0])
        if not deterministic:
            self._key, sub = jax.random.split(self._key)
            mu = mu + np.exp(np.asarray(log_std[0])) * \
                np.asarray(jax.random.normal(sub, mu.shape))
        return (np.tanh(mu) * self._scale +
                self._center).astype(np.float32)

    def get_state(self) -> Dict[str, Any]:
        import jax
        return {"state": jax.device_get(self._state)}

    def set_state(self, state: Dict[str, Any]) -> None:
        import jax.numpy as jnp
        s = state["state"]
        s["log_alpha"] = jnp.asarray(s["log_alpha"])
        self._state = s
        self._opt_state = {
            "pi": self._opt.init(self._state["pi"]),
            "q": self._opt.init(self._state["q"]),
            "alpha": self._opt.init(self._state["log_alpha"]),
        }
        self._sync_weights()

    def stop(self):
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
