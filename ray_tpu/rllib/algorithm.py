"""Algorithm/AlgorithmConfig base: the RLlib surface shape.

Capability parity with the reference's builder-pattern AlgorithmConfig
(rllib/algorithms/algorithm_config.py — ``.environment().rollouts()
.training().resources()`` chaining, ``.build()``) and the Algorithm
Trainable contract (rllib/algorithms/algorithm.py:145 — ``train()`` one
iteration, save/restore checkpoints, nests under Tune like any
trainable). TPU-native stance per BASELINE.md: learners are jitted JAX
updates (TPU when present), rollout workers are CPU actors.
"""
from __future__ import annotations

import copy
import pickle
from typing import Any, Callable, Dict, List, Optional, Type

from ray_tpu.rllib.env import ENV_REGISTRY


def register_env(name: str, creator: Callable[[], Any]) -> None:
    """Register a custom env constructor (reference: ray.tune
    register_env used throughout rllib)."""
    ENV_REGISTRY[name] = creator


class AlgorithmConfig:
    """Chainable config; subclasses add algorithm-specific fields via
    ``_defaults()``."""

    def __init__(self):
        self.env: str = "CartPole"
        self.num_rollout_workers: int = 2
        self.rollout_fragment_length: int = 256
        self.gamma: float = 0.99
        self.lr: float = 3e-4
        self.hidden_size: int = 64
        self.seed: int = 0
        self.num_tpus_for_learner: float = 0.0
        for k, v in self._defaults().items():
            setattr(self, k, v)

    def _defaults(self) -> Dict[str, Any]:
        return {}

    # --- chaining sections (reference surface) ----------------------------

    def environment(self, env: Optional[str] = None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, num_rollout_workers: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None
                 ) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(
                    f"{type(self).__name__} has no training field {k!r}")
            setattr(self, k, v)
        return self

    def resources(self, num_tpus_for_learner: Optional[float] = None
                  ) -> "AlgorithmConfig":
        if num_tpus_for_learner is not None:
            self.num_tpus_for_learner = num_tpus_for_learner
        return self

    def debugging(self, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in vars(self).items()
                if not k.startswith("_")}

    def algo_class(self) -> Type["Algorithm"]:
        raise NotImplementedError

    def build(self) -> "Algorithm":
        return self.algo_class()(self.copy())


def greedy_action(algo, obs) -> int:
    """Shared greedy compute_action for the discrete learners: jits
    the policy apply once per algorithm instance and argmaxes the
    head — handles both (logits, value) actor-critic outputs (PPO/
    A2C/IMPALA) and plain Q outputs (DQN)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    fn = getattr(algo, "_eval_apply", None)
    if fn is None:
        model = getattr(algo, "model", None) or algo._model
        algo._eval_params_attr = ("params" if hasattr(algo, "params")
                                  else "_params")
        fn = algo._eval_apply = jax.jit(model.apply)
    out = fn(getattr(algo, algo._eval_params_attr),
             jnp.asarray(obs)[None])
    head = out[0] if isinstance(out, tuple) else out
    return int(np.asarray(head[0]).argmax())


def rollout_evaluate(algo, num_episodes: int = 5,
                     seed: int = 1000) -> Dict[str, Any]:
    """Deterministic policy evaluation by env rollout (reference:
    Algorithm.evaluate / evaluation WorkerSet — here the driver rolls
    out with algo.compute_action, enough for the builtin envs)."""
    env = ENV_REGISTRY[algo.config.env]()
    returns, lengths = [], []
    for ep in range(num_episodes):
        obs = env.reset(seed=seed + ep)
        done, total, n = False, 0.0, 0
        while not done:
            obs, reward, done, _ = env.step(algo.compute_action(obs))
            total += reward
            n += 1
        returns.append(total)
        lengths.append(n)
    return {"evaluation": {
        "episode_reward_mean": float(sum(returns) / len(returns)),
        "episode_reward_min": float(min(returns)),
        "episode_reward_max": float(max(returns)),
        "episode_len_mean": float(sum(lengths) / len(lengths)),
        "episodes_this_iter": num_episodes,
    }}


class Algorithm:
    """One-iteration-at-a-time trainer (Trainable contract)."""

    def __init__(self, config: AlgorithmConfig):
        from ray_tpu._private.usage_stats import record_library_usage
        record_library_usage("rllib")
        self.config = config
        self.iteration = 0
        self._setup()

    def _setup(self) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def compute_action(self, obs):
        """Action for one observation from the learned policy
        (deterministic; reference: Policy.compute_single_action)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement compute_action")

    def evaluate(self, num_episodes: int = 5,
                 seed: int = 1000) -> Dict[str, Any]:
        """Roll out the deterministic policy (reference:
        Algorithm.evaluate)."""
        return rollout_evaluate(self, num_episodes, seed)

    def train(self) -> Dict[str, Any]:
        result = self.training_step()
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        return result

    # --- checkpointing (Trainable.save/restore parity) --------------------

    def get_state(self) -> Dict[str, Any]:
        raise NotImplementedError

    def set_state(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    def save(self, path: str) -> str:
        with open(path, "wb") as f:
            pickle.dump({"iteration": self.iteration,
                         "state": self.get_state()}, f)
        return path

    def restore(self, path: str) -> None:
        with open(path, "rb") as f:
            blob = pickle.load(f)
        self.iteration = blob["iteration"]
        self.set_state(blob["state"])

    def stop(self) -> None:
        pass

    # --- Tune integration -------------------------------------------------

    @classmethod
    def as_trainable(cls, base_config: AlgorithmConfig):
        def trainable(config: Dict[str, Any]):
            from ray_tpu.air import session
            cfg = base_config.copy()
            for k, v in config.items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
            iters = config.get("training_iterations", 10)
            algo = cfg.build()
            try:
                for _ in range(iters):
                    session.report(algo.train())
            finally:
                algo.stop()
        return trainable
