"""A2C: synchronous advantage actor-critic.

Capability parity with the reference's A2C
(rllib/algorithms/a2c/a2c.py — synchronous parallel sampling + one
policy-gradient step per iteration; PPO minus the clipped surrogate
and the multi-epoch SGD). Reuses PPO's rollout-worker actors and GAE;
the learner is ONE jitted actor-critic update per iteration.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import ENV_REGISTRY
from ray_tpu.rllib.ppo import PPO, RolloutWorker, _policy_defs


@dataclasses.dataclass
class A2CConfig:
    env: str = "CartPole"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 256
    gamma: float = 0.99
    gae_lambda: float = 1.0          # classic A2C: plain returns
    lr: float = 7e-4
    hidden_size: int = 64
    vf_coef: float = 0.5
    entropy_coef: float = 0.01
    seed: int = 0

    def build(self) -> "A2C":
        return A2C(self)


class A2C:
    def __init__(self, config: A2CConfig):
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        probe = ENV_REGISTRY[config.env]()
        self.model = _policy_defs(probe.observation_dim,
                                  probe.num_actions, config.hidden_size)
        self.params = self.model.init(
            jax.random.PRNGKey(config.seed),
            jnp.zeros((1, probe.observation_dim)))
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._iteration = 0
        worker_cls = ray_tpu.remote(RolloutWorker)
        self.workers = [
            worker_cls.options(num_cpus=0.5).remote(
                config.env, config.hidden_size, config.seed + i)
            for i in range(config.num_rollout_workers)]
        self._update = self._build_update()

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax
        cfg = self.config
        model, optimizer = self.model, self.optimizer

        def loss_fn(params, batch):
            logits, values = model.apply(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=-1)[:, 0]
            pg_loss = -jnp.mean(logp * batch["adv"])
            vf_loss = jnp.mean((values - batch["returns"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            return (pg_loss + cfg.vf_coef * vf_loss -
                    cfg.entropy_coef * entropy)

        @jax.jit
        def update(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            return optax.apply_updates(params, updates), opt_state, \
                loss

        return update

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        cfg = self.config
        t0 = time.time()
        weights_ref = ray_tpu.put(self.params)
        ray_tpu.get([w.set_weights.remote(weights_ref)
                     for w in self.workers])
        batches = ray_tpu.get(
            [w.sample.remote(cfg.rollout_fragment_length)
             for w in self.workers])
        obs, act, adv, ret = [], [], [], []
        for b in batches:
            a, r = PPO._gae(b, cfg.gamma, cfg.gae_lambda)
            obs.append(b["obs"])
            act.append(b["actions"])
            adv.append(a)
            ret.append(r)
        adv = np.concatenate(adv)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        batch = {"obs": jnp.asarray(np.concatenate(obs)),
                 "actions": jnp.asarray(np.concatenate(act)),
                 "adv": jnp.asarray(adv),
                 "returns": jnp.asarray(np.concatenate(ret))}
        self.params, self.opt_state, loss = self._update(
            self.params, self.opt_state, batch)
        self._iteration += 1
        rewards = [r for w in ray_tpu.get(
            [w.episode_rewards.remote() for w in self.workers])
            for r in w]
        return {
            "training_iteration": self._iteration,
            "loss": float(loss),
            "episode_reward_mean": float(np.mean(rewards))
            if rewards else float("nan"),
            "num_env_steps_sampled":
                cfg.rollout_fragment_length * len(self.workers),
            "time_this_iter_s": time.time() - t0,
        }

    def get_policy_params(self):
        return self.params

    def compute_action(self, obs):
        """Greedy action from the learned policy (reference:
        Policy.compute_single_action)."""
        from ray_tpu.rllib.algorithm import greedy_action
        return greedy_action(self, obs)

    def evaluate(self, num_episodes: int = 5, seed: int = 1000):
        """Deterministic rollout eval (reference: Algorithm.evaluate)."""
        from ray_tpu.rllib.algorithm import rollout_evaluate
        return rollout_evaluate(self, num_episodes, seed)

    def stop(self):
        for w in self.workers:
            ray_tpu.kill(w)
