"""RL library: Algorithm/AlgorithmConfig surface with PPO/A2C (sync
on-policy), DQN (off-policy replay), SAC (continuous control, twin
critics + auto temperature), IMPALA (async actor-learner with
V-trace), offline BC/CQL over ray_tpu.data transition datasets, and
multi-agent PPO (dict-keyed envs, per-policy mapping) over CPU rollout
actors + jitted JAX learners (TPU when present).
Reference: rllib/ (SURVEY.md §2.3 L7, §3.6)."""
from ray_tpu.rllib.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithm import (Algorithm, AlgorithmConfig,
                                     register_env)
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env import (CartPoleEnv, PendulumEnv, ReachEnv,
                               SignEnv)
from ray_tpu.rllib.impala import Impala, ImpalaConfig
from ray_tpu.rllib.multi_agent import (MultiAgentEnv, MultiAgentPPO,
                                       MultiAgentPPOConfig,
                                       MultiCartPole)
from ray_tpu.rllib.offline import (BC, BCConfig, CQL, CQLConfig,
                                   episodes_to_dataset)
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = [
    "Algorithm", "AlgorithmConfig", "register_env",
    "PPO", "PPOConfig", "A2C", "A2CConfig", "DQN", "DQNConfig",
    "Impala", "ImpalaConfig", "SAC", "SACConfig",
    "BC", "BCConfig", "CQL", "CQLConfig",
    "episodes_to_dataset", "MultiAgentEnv", "MultiAgentPPO",
    "MultiAgentPPOConfig", "MultiCartPole",
    "CartPoleEnv", "PendulumEnv", "ReachEnv", "SignEnv",
]
