from ray_tpu.rllib.env import CartPoleEnv, SignEnv
from ray_tpu.rllib.ppo import PPO, PPOConfig

__all__ = ["PPO", "PPOConfig", "CartPoleEnv", "SignEnv"]
