"""RL library: Algorithm/AlgorithmConfig surface with PPO (sync
on-policy), DQN (off-policy replay) and IMPALA (async actor-learner with
V-trace) over CPU rollout actors + a jitted JAX learner (TPU when
present). Reference: rllib/ (SURVEY.md §2.3 L7, §3.6)."""
from ray_tpu.rllib.algorithm import (Algorithm, AlgorithmConfig,
                                     register_env)
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env import CartPoleEnv, SignEnv
from ray_tpu.rllib.impala import Impala, ImpalaConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig

__all__ = [
    "Algorithm", "AlgorithmConfig", "register_env",
    "PPO", "PPOConfig", "DQN", "DQNConfig", "Impala", "ImpalaConfig",
    "CartPoleEnv", "SignEnv",
]
