"""DQN: off-policy value learning with replay + target network.

Capability parity with the reference's DQN family
(rllib/algorithms/dqn/dqn.py — replay-buffer training_step, target
network sync every N steps, epsilon-greedy exploration on rollout
workers; double-DQN action selection per the default config). The
learner is one jitted update (TPU when present); rollout workers are
CPU actors sampling with the current epsilon.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import ENV_REGISTRY


def _q_net(obs_dim: int, num_actions: int, hidden: int):
    import flax.linen as nn

    class QNet(nn.Module):
        @nn.compact
        def __call__(self, obs):
            h = nn.relu(nn.Dense(hidden)(obs))
            h = nn.relu(nn.Dense(hidden)(h))
            return nn.Dense(num_actions)(h)

    return QNet()


class ReplayBuffer:
    """Uniform FIFO replay (reference:
    rllib/utils/replay_buffers/replay_buffer.py). Action storage is
    parameterized so continuous learners (SAC) share this buffer:
    scalar int32 actions by default, float vectors via action_shape."""

    def __init__(self, capacity: int, obs_dim: int,
                 action_shape: tuple = (), action_dtype=np.int32):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity,) + action_shape, action_dtype)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.bool_)
        self.size = 0
        self._next = 0

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(batch["actions"])
        for i in range(n):
            j = self._next
            self.obs[j] = batch["obs"][i]
            self.next_obs[j] = batch["next_obs"][i]
            self.actions[j] = batch["actions"][i]
            self.rewards[j] = batch["rewards"][i]
            self.dones[j] = batch["dones"][i]
            self._next = (self._next + 1) % self.capacity
            self.size = min(self.size + 1, self.capacity)

    def sample(self, batch_size: int,
               rng: np.random.RandomState) -> Dict[str, np.ndarray]:
        idx = rng.randint(0, self.size, size=batch_size)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx],
                "rewards": self.rewards[idx], "dones": self.dones[idx]}


class DQNRolloutWorker:
    """CPU actor: epsilon-greedy transitions with the current Q-net."""

    def __init__(self, env_name: str, hidden: int, seed: int):
        self.env = ENV_REGISTRY[env_name]()
        self.obs = self.env.reset(seed=seed)
        self._rng = np.random.RandomState(seed)
        self._params = None
        self._model = _q_net(self.env.observation_dim,
                             self.env.num_actions, hidden)
        self._apply = None
        self._episode_reward = 0.0
        self.completed_rewards: List[float] = []

    def set_weights(self, params):
        self._params = params

    def sample(self, num_steps: int, epsilon: float
               ) -> Dict[str, np.ndarray]:
        import jax
        import jax.numpy as jnp
        if self._apply is None:
            self._apply = jax.jit(self._model.apply)
        obs_b, nobs_b, act_b, rew_b, done_b = [], [], [], [], []
        for _ in range(num_steps):
            if self._rng.rand() < epsilon:
                action = int(self._rng.randint(self.env.num_actions))
            else:
                q = self._apply(self._params, jnp.asarray(self.obs[None]))
                action = int(np.asarray(q[0]).argmax())
            next_obs, reward, done, _ = self.env.step(action)
            obs_b.append(self.obs)
            nobs_b.append(next_obs)
            act_b.append(action)
            rew_b.append(reward)
            done_b.append(done)
            self._episode_reward += reward
            if done:
                self.completed_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        return {"obs": np.asarray(obs_b, np.float32),
                "next_obs": np.asarray(nobs_b, np.float32),
                "actions": np.asarray(act_b, np.int32),
                "rewards": np.asarray(rew_b, np.float32),
                "dones": np.asarray(done_b, np.bool_)}

    def episode_rewards(self) -> List[float]:
        return list(self.completed_rewards[-100:])


class DQNConfig(AlgorithmConfig):
    def _defaults(self) -> Dict[str, Any]:
        return {
            "replay_buffer_capacity": 50_000,
            "learning_starts": 500,
            "train_batch_size": 64,
            "num_sgd_iter_per_step": 8,
            "target_network_update_freq": 4,   # in training iterations
            "epsilon_initial": 1.0,
            "epsilon_final": 0.05,
            "epsilon_decay_iters": 20,
            "double_q": True,
            "rollout_fragment_length": 128,
        }

    def algo_class(self):
        return DQN


class DQN(Algorithm):
    def _setup(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        env = ENV_REGISTRY[cfg.env]()
        self._obs_dim = env.observation_dim
        self._num_actions = env.num_actions
        self._model = _q_net(self._obs_dim, self._num_actions,
                             cfg.hidden_size)
        key = jax.random.PRNGKey(cfg.seed)
        self._params = self._model.init(
            key, jnp.zeros((1, self._obs_dim), jnp.float32))
        self._target_params = self._params
        self._opt = optax.adam(cfg.lr)
        self._opt_state = self._opt.init(self._params)
        self._rng = np.random.RandomState(cfg.seed)
        self._buffer = ReplayBuffer(cfg.replay_buffer_capacity,
                                    self._obs_dim)
        worker_cls = ray_tpu.remote(num_cpus=1)(DQNRolloutWorker)
        self._workers = [
            worker_cls.remote(cfg.env, cfg.hidden_size, cfg.seed + i)
            for i in range(cfg.num_rollout_workers)]
        self._sync_weights()
        self._update = self._build_update()

    def _build_update(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        model = self._model
        gamma = cfg.gamma
        double_q = cfg.double_q
        opt = self._opt

        def loss_fn(params, target_params, batch):
            q = model.apply(params, batch["obs"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1)[:, 0]
            q_next_target = model.apply(target_params, batch["next_obs"])
            if double_q:
                # Online net picks the action, target net evaluates it.
                q_next_online = model.apply(params, batch["next_obs"])
                next_a = jnp.argmax(q_next_online, axis=1)
                next_q = jnp.take_along_axis(
                    q_next_target, next_a[:, None], axis=1)[:, 0]
            else:
                next_q = q_next_target.max(axis=1)
            target = batch["rewards"] + gamma * next_q * \
                (1.0 - batch["dones"].astype(jnp.float32))
            td = q_taken - jax.lax.stop_gradient(target)
            return jnp.mean(td ** 2)

        @jax.jit
        def update(params, opt_state, target_params, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, target_params, batch)
            updates, opt_state = opt.update(grads, opt_state, params)
            import optax as _optax
            params = _optax.apply_updates(params, updates)
            return params, opt_state, loss

        return update

    def _sync_weights(self):
        import jax
        host = jax.device_get(self._params)
        ray_tpu.get([w.set_weights.remote(host) for w in self._workers])

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.config
        t0 = time.time()
        eps = self._epsilon()
        batches = ray_tpu.get([
            w.sample.remote(cfg.rollout_fragment_length, eps)
            for w in self._workers])
        for b in batches:
            self._buffer.add_batch(b)
        steps = sum(len(b["actions"]) for b in batches)
        losses = []
        if self._buffer.size >= cfg.learning_starts:
            for _ in range(cfg.num_sgd_iter_per_step):
                mb = self._buffer.sample(cfg.train_batch_size, self._rng)
                mb = {k: jnp.asarray(v) for k, v in mb.items()}
                self._params, self._opt_state, loss = self._update(
                    self._params, self._opt_state,
                    self._target_params, mb)
                losses.append(float(loss))
            if (self.iteration + 1) % cfg.target_network_update_freq == 0:
                self._target_params = self._params
            self._sync_weights()
        rewards: List[float] = []
        for w in self._workers:
            rewards.extend(ray_tpu.get(w.episode_rewards.remote()))
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "num_env_steps_sampled": steps,
            "buffer_size": self._buffer.size,
            "epsilon": eps,
            "loss": float(np.mean(losses)) if losses else None,
            "time_this_iter_s": time.time() - t0,
        }

    def compute_action(self, obs) -> int:
        """Greedy argmax-Q action (reference:
        Policy.compute_single_action with explore=False)."""
        from ray_tpu.rllib.algorithm import greedy_action
        return greedy_action(self, obs)

    def get_state(self) -> Dict[str, Any]:
        import jax
        return {"params": jax.device_get(self._params),
                "target_params": jax.device_get(self._target_params)}

    def set_state(self, state: Dict[str, Any]) -> None:
        self._params = state["params"]
        self._target_params = state["target_params"]
        self._opt_state = self._opt.init(self._params)
        self._sync_weights()

    def stop(self):
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
