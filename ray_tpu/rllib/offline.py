"""Offline RL: behavior cloning (BC) and conservative Q-learning (CQL).

Capability parity with the reference's offline stack
(rllib/algorithms/bc/bc.py, rllib/algorithms/cql/cql.py, offline data
via ray.data — rllib/offline/): transitions live in a
ray_tpu.data.Dataset of row dicts {obs, action, reward, next_obs,
done}; learners are jitted JAX updates over shuffled minibatches
(no environment interaction — pure dataset training).

TPU-native stance: the whole offline epoch (scan over minibatches) is
one compiled program, matching the online learners.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import ENV_REGISTRY


def episodes_to_dataset(rollouts: List[Dict[str, np.ndarray]]):
    """Turn rollout-worker sample batches into an offline transition
    Dataset (the reference writes JSON sample batches via
    rllib/offline/json_writer.py; here blocks go straight into the
    object store)."""
    from ray_tpu.data import Dataset
    blocks = []
    for b in rollouts:
        rows = []
        n = len(b["actions"])
        for t in range(n):
            next_obs = b["obs"][t + 1] if t + 1 < n else b["obs"][t]
            rows.append({
                "obs": np.asarray(b["obs"][t], np.float32),
                "action": int(b["actions"][t]),
                "reward": float(b["rewards"][t]),
                "next_obs": np.asarray(next_obs, np.float32),
                "done": bool(b["dones"][t]),
            })
        blocks.append(ray_tpu.put(rows))
    return Dataset(blocks)


def _dataset_arrays(dataset) -> Dict[str, np.ndarray]:
    rows = dataset.take_all()
    return {
        "obs": np.asarray([r["obs"] for r in rows], np.float32),
        "action": np.asarray([r["action"] for r in rows], np.int32),
        "reward": np.asarray([r["reward"] for r in rows], np.float32),
        "next_obs": np.asarray([r["next_obs"] for r in rows],
                               np.float32),
        "done": np.asarray([r["done"] for r in rows], np.bool_),
    }


@dataclasses.dataclass
class BCConfig:
    env: str = "CartPole"          # for obs/action dims only
    lr: float = 1e-3
    hidden_size: int = 64
    batch_size: int = 256
    seed: int = 0

    def build(self, dataset) -> "BC":
        return BC(self, dataset)


class BC:
    """Behavior cloning: supervised cross-entropy on dataset actions
    (rllib/algorithms/bc/bc.py — MARWIL with beta=0)."""

    def __init__(self, config: BCConfig, dataset):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rllib.ppo import _policy_defs
        self.config = config
        probe = ENV_REGISTRY[config.env]()
        self.model = _policy_defs(probe.observation_dim,
                                  probe.num_actions,
                                  config.hidden_size)
        self.params = self.model.init(
            jax.random.PRNGKey(config.seed),
            jnp.zeros((1, probe.observation_dim)))
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.data = _dataset_arrays(dataset)
        self._rng = np.random.RandomState(config.seed)
        self._iteration = 0
        model, optimizer = self.model, self.optimizer

        def loss_fn(params, mb):
            logits, _ = model.apply(params, mb["obs"])
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, mb["action"][:, None], axis=-1)[:, 0]
            return jnp.mean(nll)

        @jax.jit
        def update(params, opt_state, mb):
            loss, grads = jax.value_and_grad(loss_fn)(params, mb)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            return optax.apply_updates(params, updates), opt_state, \
                loss

        self._update = update

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        n = len(self.data["action"])
        idx = self._rng.choice(n, size=min(self.config.batch_size, n),
                               replace=False)
        mb = {"obs": jnp.asarray(self.data["obs"][idx]),
              "action": jnp.asarray(self.data["action"][idx])}
        self.params, self.opt_state, loss = self._update(
            self.params, self.opt_state, mb)
        self._iteration += 1
        return {"training_iteration": self._iteration,
                "loss": float(loss)}

    def compute_action(self, obs: np.ndarray) -> int:
        import jax.numpy as jnp
        logits, _ = self.model.apply(self.params,
                                     jnp.asarray(obs[None]))
        return int(np.argmax(np.asarray(logits)[0]))


@dataclasses.dataclass
class CQLConfig:
    env: str = "CartPole"
    lr: float = 5e-4
    hidden_size: int = 64
    batch_size: int = 256
    gamma: float = 0.99
    cql_alpha: float = 1.0         # conservative penalty weight
    target_update_every: int = 20
    seed: int = 0

    def build(self, dataset) -> "CQL":
        return CQL(self, dataset)


class CQL:
    """Discrete conservative Q-learning
    (rllib/algorithms/cql/cql.py; Kumar et al. 2020): DQN's TD loss
    plus alpha * (logsumexp_a Q(s,a) - Q(s, a_data)) — pushing down
    out-of-distribution action values so the offline policy never
    exploits unobserved actions."""

    def __init__(self, config: CQLConfig, dataset):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rllib.dqn import _q_net
        self.config = config
        probe = ENV_REGISTRY[config.env]()
        self.model = _q_net(probe.observation_dim, probe.num_actions,
                            config.hidden_size)
        self.params = self.model.init(
            jax.random.PRNGKey(config.seed),
            jnp.zeros((1, probe.observation_dim)))
        self.target_params = self.params
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.data = _dataset_arrays(dataset)
        self._rng = np.random.RandomState(config.seed)
        self._iteration = 0
        model, optimizer, cfg = self.model, self.optimizer, config

        def loss_fn(params, target_params, mb):
            q = model.apply(params, mb["obs"])
            q_data = jnp.take_along_axis(
                q, mb["action"][:, None], axis=-1)[:, 0]
            q_next = model.apply(target_params, mb["next_obs"])
            target = mb["reward"] + cfg.gamma * \
                (1.0 - mb["done"]) * jnp.max(q_next, axis=-1)
            td = jnp.mean((q_data - jax.lax.stop_gradient(target))
                          ** 2)
            conservative = jnp.mean(
                jax.scipy.special.logsumexp(q, axis=-1) - q_data)
            return td + cfg.cql_alpha * conservative, \
                (td, conservative)

        @jax.jit
        def update(params, target_params, opt_state, mb):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, mb)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            return optax.apply_updates(params, updates), opt_state, \
                loss, aux

        self._update = update

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        cfg = self.config
        n = len(self.data["action"])
        idx = self._rng.choice(n, size=min(cfg.batch_size, n),
                               replace=False)
        mb = {k: jnp.asarray(v[idx].astype(np.float32)
                             if k in ("reward",) else v[idx])
              for k, v in self.data.items()}
        mb["done"] = mb["done"].astype(jnp.float32)
        self.params, self.opt_state, loss, (td, cons) = self._update(
            self.params, self.target_params, self.opt_state, mb)
        self._iteration += 1
        if self._iteration % cfg.target_update_every == 0:
            self.target_params = self.params
        return {"training_iteration": self._iteration,
                "loss": float(loss), "td_loss": float(td),
                "conservative_gap": float(cons)}

    def compute_action(self, obs: np.ndarray) -> int:
        import jax.numpy as jnp
        q = self.model.apply(self.params, jnp.asarray(obs[None]))
        return int(np.argmax(np.asarray(q)[0]))
