"""PPO: the minimum-viable RL algorithm of the framework.

Capability parity with the reference's PPO training loop
(rllib/algorithms/ppo/ppo.py:401 training_step,
execution/rollout_ops.py:36 synchronous_parallel_sample,
execution/train_ops.py train_one_step, evaluation/rollout_worker.py:124):
CPU rollout-worker ACTORS sample episodes with the current policy; the
driver-side LEARNER does minibatch clipped-PPO SGD as ONE jitted update per
epoch (scan over minibatches) — on TPU when available, per the BASELINE.md
target config ("RLlib PPO, TPU learner + CPU rollout workers") — then
broadcasts new weights to the workers.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import ENV_REGISTRY


# --------------------------------------------------------------------------
# Loss pieces (module level so external learners — e.g. ray_tpu.rl — can
# reuse the exact clipped-surrogate objective on token-level batches)
# --------------------------------------------------------------------------

def clipped_surrogate_loss(logp, behavior_logp, adv, clip_eps):
    """Clipped-PPO policy-gradient loss.

    ``logp`` is the current policy's log-prob of the taken action,
    ``behavior_logp`` the log-prob under the policy that generated the
    data. All three arrays share a leading axis; returns a scalar.
    """
    import jax.numpy as jnp

    ratio = jnp.exp(logp - behavior_logp)
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv
    return -jnp.mean(jnp.minimum(unclipped, clipped))


# --------------------------------------------------------------------------
# Policy network (jax/flax actor-critic MLP)
# --------------------------------------------------------------------------

def _policy_defs(obs_dim: int, num_actions: int, hidden: int):
    import flax.linen as nn

    class ActorCritic(nn.Module):
        @nn.compact
        def __call__(self, obs):
            h = nn.tanh(nn.Dense(hidden)(obs))
            h = nn.tanh(nn.Dense(hidden)(h))
            logits = nn.Dense(num_actions)(h)
            value = nn.Dense(1)(h)[..., 0]
            return logits, value

    return ActorCritic()


@dataclasses.dataclass
class PPOConfig:
    env: str = "CartPole"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 256
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    lr: float = 3e-4
    num_sgd_epochs: int = 4
    minibatch_size: int = 128
    hidden_size: int = 64
    vf_coef: float = 0.5
    entropy_coef: float = 0.01
    seed: int = 0

    def build(self) -> "PPO":
        return PPO(self)


# --------------------------------------------------------------------------
# Rollout worker actor (CPU)
# --------------------------------------------------------------------------

class RolloutWorker:
    def __init__(self, env_name: str, hidden: int, seed: int):
        self.env = ENV_REGISTRY[env_name]()
        self.obs = self.env.reset(seed=seed)
        self._rng = np.random.RandomState(seed)
        self._policy_params = None
        self._model = _policy_defs(self.env.observation_dim,
                                   self.env.num_actions, hidden)
        self._episode_reward = 0.0
        self.completed_rewards: List[float] = []

    def set_weights(self, params):
        self._policy_params = params

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect a fragment with the current policy."""
        import jax
        import jax.numpy as jnp

        obs_buf, act_buf, rew_buf, done_buf = [], [], [], []
        logp_buf, val_buf = [], []
        apply = jax.jit(self._model.apply)
        for _ in range(num_steps):
            logits, value = apply(self._policy_params,
                                  jnp.asarray(self.obs[None]))
            logits = np.asarray(logits[0], np.float64)
            probs = np.exp(logits - logits.max())
            probs /= probs.sum()
            action = int(self._rng.choice(len(probs), p=probs))
            logp = float(np.log(probs[action] + 1e-12))
            next_obs, reward, done, _ = self.env.step(action)
            obs_buf.append(self.obs)
            act_buf.append(action)
            rew_buf.append(reward)
            done_buf.append(done)
            logp_buf.append(logp)
            val_buf.append(float(value[0]))
            self._episode_reward += reward
            if done:
                self.completed_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        # Bootstrap value for the final state.
        _, last_val = apply(self._policy_params,
                            jnp.asarray(self.obs[None]))
        return {
            "obs": np.asarray(obs_buf, np.float32),
            "actions": np.asarray(act_buf, np.int32),
            "rewards": np.asarray(rew_buf, np.float32),
            "dones": np.asarray(done_buf, np.bool_),
            "logp": np.asarray(logp_buf, np.float32),
            "values": np.asarray(val_buf, np.float32),
            "last_value": float(last_val[0]),
        }

    def episode_rewards(self) -> List[float]:
        out = self.completed_rewards[-100:]
        return list(out)


# --------------------------------------------------------------------------
# Algorithm
# --------------------------------------------------------------------------

class PPO:
    def __init__(self, config: PPOConfig):
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        env_cls = ENV_REGISTRY[config.env]
        probe = env_cls()
        self.model = _policy_defs(probe.observation_dim,
                                  probe.num_actions, config.hidden_size)
        rng = jax.random.PRNGKey(config.seed)
        self.params = self.model.init(
            rng, jnp.zeros((1, probe.observation_dim)))
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._iteration = 0

        worker_cls = ray_tpu.remote(RolloutWorker)
        self.workers = [
            worker_cls.options(num_cpus=0.5).remote(
                config.env, config.hidden_size, config.seed + i)
            for i in range(config.num_rollout_workers)]
        self._update = self._build_update()

    # --- learner ----------------------------------------------------------

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax
        cfg = self.config
        model, optimizer = self.model, self.optimizer

        def loss_fn(params, mb):
            logits, values = model.apply(params, mb["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, mb["actions"][:, None], axis=-1)[:, 0]
            pg_loss = clipped_surrogate_loss(
                logp, mb["logp"], mb["adv"], cfg.clip_eps)
            vf_loss = jnp.mean((values - mb["returns"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = (pg_loss + cfg.vf_coef * vf_loss -
                     cfg.entropy_coef * entropy)
            return total, (pg_loss, vf_loss, entropy)

        def epoch(carry, mb):
            params, opt_state = carry
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        @jax.jit
        def update(params, opt_state, minibatches):
            (params, opt_state), losses = jax.lax.scan(
                epoch, (params, opt_state), minibatches)
            return params, opt_state, jnp.mean(losses)

        return update

    @staticmethod
    def _gae(batch, gamma: float, lam: float):
        rewards = batch["rewards"]
        values = batch["values"]
        dones = batch["dones"].astype(np.float32)
        T = len(rewards)
        adv = np.zeros(T, np.float32)
        last_adv = 0.0
        next_value = batch["last_value"]
        for t in reversed(range(T)):
            nonterminal = 1.0 - dones[t]
            delta = rewards[t] + gamma * next_value * nonterminal - \
                values[t]
            last_adv = delta + gamma * lam * nonterminal * last_adv
            adv[t] = last_adv
            next_value = values[t]
        returns = adv + values
        return adv, returns

    def train_on_batch(self, data: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Minibatch clipped-PPO SGD over an externally supplied batch.

        ``data`` needs ``obs``, ``actions``, ``logp`` (behavior log-probs),
        ``adv`` and ``returns``, all index-aligned on the leading axis.
        Advantages are normalized here. This is the consume-external-
        rollouts surface used by ray_tpu.rl; ``train()`` delegates to it
        after sampling from its own workers.
        """
        import jax.numpy as jnp

        cfg = self.config
        adv = np.asarray(data["adv"], np.float32)
        data = dict(data)
        data["adv"] = (adv - adv.mean()) / (adv.std() + 1e-8)

        n = len(data["obs"])
        mb_size = min(cfg.minibatch_size, n)
        rng = np.random.RandomState(cfg.seed + self._iteration)
        mbs = []
        for _ in range(cfg.num_sgd_epochs):
            perm = rng.permutation(n)
            for i in range(0, n - mb_size + 1, mb_size):
                idx = perm[i:i + mb_size]
                mbs.append({k: v[idx] for k, v in data.items()})
        stacked = {k: jnp.asarray(np.stack([m[k] for m in mbs]))
                   for k in mbs[0]}
        self.params, self.opt_state, mean_loss = self._update(
            self.params, self.opt_state, stacked)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter": n,
            "loss": float(mean_loss),
        }

    def train(self) -> Dict[str, Any]:
        """One iteration: parallel sample -> GAE -> minibatch SGD ->
        broadcast weights."""
        cfg = self.config
        t0 = time.time()
        weights_ref = ray_tpu.put(self.params)
        ray_tpu.get([w.set_weights.remote(weights_ref)
                     for w in self.workers])
        batches = ray_tpu.get([
            w.sample.remote(cfg.rollout_fragment_length)
            for w in self.workers])

        advs, rets = [], []
        for b in batches:
            a, r = self._gae(b, cfg.gamma, cfg.gae_lambda)
            advs.append(a)
            rets.append(r)
        data = {
            "obs": np.concatenate([b["obs"] for b in batches]),
            "actions": np.concatenate([b["actions"] for b in batches]),
            "logp": np.concatenate([b["logp"] for b in batches]),
            "adv": np.concatenate(advs),
            "returns": np.concatenate(rets),
        }
        stats = self.train_on_batch(data)

        reward_lists = ray_tpu.get(
            [w.episode_rewards.remote() for w in self.workers])
        all_rewards = [r for lst in reward_lists for r in lst]
        stats.update({
            "episode_reward_mean": (float(np.mean(all_rewards))
                                    if all_rewards else float("nan")),
            "episodes_total": len(all_rewards),
            "time_this_iter_s": time.time() - t0,
        })
        return stats

    def get_policy_params(self):
        return self.params

    def compute_action(self, obs):
        """Greedy action from the learned policy (reference:
        Policy.compute_single_action)."""
        from ray_tpu.rllib.algorithm import greedy_action
        return greedy_action(self, obs)

    def evaluate(self, num_episodes: int = 5, seed: int = 1000):
        """Deterministic rollout eval (reference: Algorithm.evaluate)."""
        from ray_tpu.rllib.algorithm import rollout_evaluate
        return rollout_evaluate(self, num_episodes, seed)

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    # Tune integration: a function trainable.
    @staticmethod
    def as_trainable(base_config: Optional[Dict[str, Any]] = None):
        def trainable(config):
            from ray_tpu.air import session
            merged = dict(base_config or {})
            merged.update({k: v for k, v in config.items()
                           if k in PPOConfig.__dataclass_fields__})
            iters = config.get("training_iterations", 10)
            algo = PPOConfig(**merged).build()
            try:
                for _ in range(iters):
                    session.report(algo.train())
            finally:
                algo.stop()
        return trainable
