"""ray_tpu: a TPU-native distributed AI runtime.

Capability surface of the reference Ray (tasks, actors, objects, placement
groups, Train/Tune/Serve/Data/RL libraries) rebuilt TPU-first: JAX/XLA/pjit
for all device compute, mesh-sharded SPMD gangs as first-class scheduling
units, collectives in-band over ICI/DCN, device arrays referenced (never
copied) by the object layer. See SURVEY.md for the design blueprint.
"""

__version__ = "0.1.0"

from ray_tpu._private.worker import init, shutdown, is_initialized
from ray_tpu.api import (
    ActorClass,
    ActorHandle,
    RemoteFunction,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    kill,
    nodes,
    put,
    remote,
    timeline,
    wait,
)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.runtime_context import RuntimeContext, get_runtime_context
from ray_tpu import exceptions
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
)

# Subpackages (imported lazily by users): ray_tpu.mesh, ray_tpu.train,
# ray_tpu.tune, ray_tpu.serve, ray_tpu.data, ray_tpu.rllib, ray_tpu.util


def method(**kwargs):
    """Decorator for actor methods to set per-method defaults
    (num_returns), reference: python/ray/actor.py ``@ray.method``."""
    def wrapper(f):
        f.__ray_tpu_method_opts__ = kwargs
        return f
    return wrapper


__all__ = [
    "RuntimeContext", "get_runtime_context",
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "cancel", "kill", "get_actor", "ObjectRef", "ActorClass", "ActorHandle",
    "RemoteFunction", "cluster_resources", "available_resources",
    "nodes",
    "timeline", "method", "exceptions", "TaskError", "ActorDiedError",
    "ObjectLostError", "GetTimeoutError", "TaskCancelledError",
]
