"""Sequence/context parallelism: ring attention and Ulysses.

The reference has none of this (SURVEY.md §2.4: zero TP/SP hits — its only
scaling axis is data). These are the TPU-native long-context obligations:

- ring_attention: blockwise-softmax attention where each device holds a
  sequence chunk and K/V chunks rotate around the mesh axis via
  `lax.ppermute` (neighbor exchange rides ICI). O(T/n) activation memory
  per device; compute overlaps the rotation.
- ulysses_attention: all-to-all re-shard — trade sequence sharding for head
  sharding, run full-sequence attention on 1/n of the heads locally, and
  all-to-all back. One big collective, DCN-friendly.

Both are written to run INSIDE `jax.shard_map` over a mesh `sequence` axis;
`sequence_sharded_attention` is the outside-jit convenience wrapper.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attention(q, k, v, qpos, kpos, causal: bool):
    """One Q-chunk x K-chunk block. q:[B,Tq,H,D] k/v:[B,Tk,H,D].
    Returns (o_partial [B,Tq,H,D] fp32, m [B,H,Tq] fp32, l [B,H,Tq] fp32).
    """
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = kpos[None, :] <= qpos[:, None]          # [Tq, Tk]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                            # [B,H,Tq]
    # Fully-masked rows: keep m finite so exp() underflows to 0 cleanly.
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe[..., None])                 # [B,H,Tq,Tk]
    l = jnp.sum(p, axis=-1)                            # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m_safe, l


def ring_attention(q, k, v, axis_name: str = "sequence",
                   causal: bool = True) -> jax.Array:
    """Blockwise ring attention over `axis_name`. Call inside shard_map;
    q/k/v are local chunks [B, T_local, H, D] of the sequence-sharded
    arrays. Returns the local output chunk in q.dtype."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape
    my_qpos = idx * T + jnp.arange(T)

    def body(s, carry):
        o, m, l, kc, vc, src = carry
        kpos = src * T + jnp.arange(T)
        o_b, m_b, l_b = _block_attention(q, kc, vc, my_qpos, kpos, causal)
        m_new = jnp.maximum(m, m_b)
        alpha = jnp.exp(m - m_new)                     # rescale old
        beta = jnp.exp(m_b - m_new)                    # rescale block
        o = o * alpha.transpose(0, 2, 1)[..., None] + \
            o_b * beta.transpose(0, 2, 1)[..., None]
        l = l * alpha + l_b * beta
        # Rotate K/V to the next device (neighbor exchange over ICI).
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        src = (src - 1) % n
        return o, m_new, l, kc, vc, src

    # Derive the accumulators FROM q so they inherit q's full
    # varying-manual-axes type: inside a multi-axis shard_map (e.g. the
    # composed pipeline x sequence x data step) q varies over every
    # sharded axis, and a carry typed narrower than the body's outputs
    # fails vma checking (jax>=0.9).
    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    bht = jnp.moveaxis(q, 1, 2)[..., 0]          # [B, H, T], q's vma
    m0 = jnp.full_like(bht, _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros_like(bht, dtype=jnp.float32)
    o, m, l, _, _, _ = jax.lax.fori_loop(
        0, n, body, (o0, m0, l0, k, v, idx))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sequence",
                      causal: bool = True,
                      attention_fn=None) -> jax.Array:
    """All-to-all head-sharded attention. Call inside shard_map with
    sequence-sharded local chunks [B, T_local, H, D]; requires H divisible
    by the axis size."""
    from ray_tpu.ops.attention import xla_attention
    attention_fn = attention_fn or xla_attention
    n = jax.lax.psum(1, axis_name)

    def seq_to_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = attention_fn(qh, kh, vh, causal=causal)
    return heads_to_seq(oh)


def sequence_sharded_attention(q, k, v, mesh: Mesh, causal: bool = True,
                               impl: str = "ring",
                               axis_name: str = "sequence") -> jax.Array:
    """Outside-jit wrapper: q/k/v are global [B,T,H,D] arrays (sharded or
    not); attention runs sequence-parallel over `axis_name` of `mesh`."""
    inner = ring_attention if impl == "ring" else ulysses_attention
    spec = P(None, axis_name, None, None)

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec)
    def run(ql, kl, vl):
        return inner(ql, kl, vl, axis_name=axis_name, causal=causal)

    return run(q, k, v)
