"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference has no PP (SURVEY.md §2.4). TPU-native design: stages are the
`pipeline` mesh axis; every device runs the same shard_map program; stage
boundaries are `lax.ppermute` neighbor pushes (point-to-point over ICI); the
schedule is a fori_loop of M + S - 1 ticks, so the whole pipeline is ONE
XLA program — no per-stage actors, no host round-trips between stages.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any,
                   x: jax.Array,
                   num_microbatches: int,
                   mesh: Mesh,
                   axis_name: str = "pipeline") -> jax.Array:
    """Run `stage_fn` as an S-stage pipeline.

    stage_fn(params_for_one_stage, activation) -> activation (same shape).
    stage_params: pytree whose leaves have a leading stage axis of size S
        (leaf shape [S, ...]); each device consumes its own slice.
    x: [B, ...] input batch (replicated across the pipeline axis).
    num_microbatches: M; B must be divisible by M.

    Returns [B, ...] output of the final stage (replicated).
    """
    S = mesh.shape[axis_name]
    B = x.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible by M={num_microbatches}")
    mb = B // num_microbatches
    M = num_microbatches

    params_spec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stage_params)

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(params_spec, P()), out_specs=P())
    def run(local_params, xfull):
        # local_params leaves: [1, ...] (this stage's slice).
        local_params = jax.tree_util.tree_map(
            lambda p: p[0], local_params)
        return pipeline_run_local(stage_fn, local_params, xfull, M,
                                  S, axis_name)

    return run(stage_params, x)


def pipeline_run_local(stage_fn: Callable[[Any, jax.Array], jax.Array],
                       local_params: Any, x: jax.Array,
                       num_microbatches: int, num_stages: int,
                       axis_name: str = "pipeline") -> jax.Array:
    """The GPipe schedule itself, for callers ALREADY inside a
    shard_map (e.g. train.compose, which also shards the batch over
    data/sequence axes). `x` is this device's local batch slice;
    `local_params` is this stage's parameter slice (no leading stage
    axis). Returns the final-stage output, replicated over the
    pipeline axis."""
    S = num_stages
    M = num_microbatches
    B = x.shape[0]
    mb = B // M
    stage = jax.lax.axis_index(axis_name)
    micro = x.reshape((M, mb) + x.shape[1:])
    # Carries derive from x (inheriting its varying axes — data/
    # sequence/... in the composed step) plus the pipeline axis the
    # schedule itself varies over (jax>=0.9 vma typing; skip the cast
    # when the caller already widened x over the pipeline axis).
    def _vary_pipeline(v):
        vma = set(getattr(jax.typeof(v), "vma", ()) or ())
        if axis_name in vma:
            return v
        return jax.lax.pcast(v, (axis_name,), to="varying")

    outputs = _vary_pipeline(jnp.zeros_like(micro))
    carry_in = _vary_pipeline(jnp.zeros_like(micro[0]))

    def tick(t, state):
        outputs, recv = state
        # Stage 0 injects microbatch t (while t < M); others use recv.
        inj = jax.lax.dynamic_index_in_dim(
            micro, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        act_in = jnp.where(stage == 0, inj, recv)
        act_out = stage_fn(local_params, act_in)
        # Valid iff this stage processed a real microbatch this tick.
        valid = jnp.logical_and(t - stage >= 0, t - stage < M)
        act_out = jnp.where(valid, act_out, jnp.zeros_like(act_out))
        # Last stage banks its result at microbatch index t-(S-1).
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        banked = jax.lax.dynamic_update_index_in_dim(
            outputs, act_out.astype(outputs.dtype), out_idx, axis=0)
        is_last = stage == S - 1
        take = jnp.logical_and(is_last, t >= S - 1)
        outputs = jnp.where(take, banked, outputs)
        # Push activation to the next stage (ring; wraps harmlessly).
        recv = jax.lax.ppermute(
            act_out, axis_name,
            [(i, (i + 1) % S) for i in range(S)])
        return outputs, recv

    outputs, _ = jax.lax.fori_loop(0, M + S - 1, tick,
                                   (outputs, carry_in))
    # Broadcast the last stage's outputs to every stage so replicated
    # out_specs over the pipeline axis are truthful.
    outputs = jax.lax.psum(
        jnp.where(stage == S - 1, outputs,
                  jnp.zeros_like(outputs)), axis_name)
    return outputs.reshape((B,) + x.shape[1:])


def stack_stage_params(per_stage_params) -> Any:
    """Stack a list of S per-stage pytrees into one pytree with a leading
    stage axis (what pipeline_apply expects)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)
