"""Expert parallelism: Switch-style MoE with experts sharded on the
`expert` mesh axis.

The reference has no EP (SURVEY.md §2.4). TPU-native design: expert weights
carry a P("expert", ...) sharding; token dispatch/combine are dense einsums
against a one-hot dispatch tensor with sharding constraints, so GSPMD lowers
the dispatch to all-to-all over ICI — no hand-written routing collectives.
Capacity-factor truncation keeps shapes static for XLA.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _maybe_constrain(x, spec: P):
    """Sharding constraint that is a no-op when no mesh is active (so the
    module also runs un-sharded, e.g. in unit tests / eval scripts)."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or mesh.empty:
            return x
        return jax.lax.with_sharding_constraint(x, spec)
    except Exception:
        return x


class SwitchMoE(nn.Module):
    """Top-1 (switch) MoE FFN block.

    Input  [B, T, d_model] -> Output [B, T, d_model].
    num_experts should be a multiple of the mesh `expert` axis size.
    """
    num_experts: int
    d_model: int
    d_ff: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    use_sharding_constraint: bool = True

    @nn.compact
    def __call__(self, x):
        B, T, D = x.shape
        E = self.num_experts
        N = B * T
        C = max(1, int(self.capacity_factor * N / E))

        tokens = x.reshape(N, D)
        router_w = self.param("router", nn.initializers.normal(0.02),
                              (D, E), jnp.float32)
        logits = tokens.astype(jnp.float32) @ router_w       # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)              # [N]
        gate = jnp.take_along_axis(probs, expert_idx[:, None],
                                   axis=-1)[:, 0]            # [N]

        # Position of each token within its expert's capacity buffer.
        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # [N,E]
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot
        pos = jnp.sum(pos_in_expert, axis=-1)                # [N]
        keep = pos < C                                       # overflow drop
        # dispatch[n, e, c] = 1 iff token n goes to slot c of expert e.
        dispatch = (jax.nn.one_hot(expert_idx, E, dtype=self.dtype) *
                    keep[:, None])[..., None] * \
            jax.nn.one_hot(pos, C, dtype=self.dtype)[:, None, :]

        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E, D, self.d_ff), jnp.float32).astype(self.dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E, self.d_ff, D), jnp.float32).astype(self.dtype)

        expert_in = jnp.einsum("nd,nec->ecd", tokens.astype(self.dtype),
                               dispatch)                     # [E,C,D]
        if self.use_sharding_constraint:
            expert_in = _maybe_constrain(expert_in,
                                         P("expert", None, None))
        h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", expert_in, w1))
        expert_out = jnp.einsum("ecf,efd->ecd", h, w2)       # [E,C,D]
        if self.use_sharding_constraint:
            expert_out = _maybe_constrain(expert_out,
                                          P("expert", None, None))

        combined = jnp.einsum("ecd,nec->nd", expert_out, dispatch)
        out = combined * gate[:, None].astype(self.dtype)
        # Load-balancing auxiliary loss (Switch Transformer eq. 4).
        frac_tokens = jnp.mean(
            jax.nn.one_hot(expert_idx, E, dtype=jnp.float32), axis=0)
        frac_probs = jnp.mean(probs, axis=0)
        self.sow("losses", "load_balance",
                 E * jnp.sum(frac_tokens * frac_probs))
        return out.reshape(B, T, D)


def moe_sharding_rules():
    """Extra rules for MoE params (merge with the model's rules)."""
    return [
        (r"moe.*/w1$", P("expert", None, "tensor")),
        (r"moe.*/w2$", P("expert", "tensor", None)),
        (r"moe.*/router$", P(None, None)),
    ]
