from ray_tpu.parallel.sequence import (ring_attention,
                                       sequence_sharded_attention,
                                       ulysses_attention)
from ray_tpu.parallel.pipeline import pipeline_apply
from ray_tpu.parallel.expert import SwitchMoE

__all__ = ["ring_attention", "ulysses_attention",
           "sequence_sharded_attention", "pipeline_apply", "SwitchMoE"]
