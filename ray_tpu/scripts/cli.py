"""CLI: `python -m ray_tpu <command>`.

Capability parity with the reference CLI (python/ray/scripts/scripts.py,
click group :61 — `ray start/stop/status/submit/timeline/memory` plus the
state CLI `ray list ...`, experimental/state/state_cli.py), over the head
RPC protocol instead of GCS.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import click

from ray_tpu.scripts.head_daemon import address_file_path


def _load_file_token():
    """Adopt the daemon-minted cluster token from the address file so
    same-machine CLI clients authenticate (an explicit
    RAY_TPU_cluster_token env var wins)."""
    if os.environ.get("RAY_TPU_cluster_token"):
        return
    from ray_tpu.scripts.head_daemon import read_address_file
    _addr, token, _pid = read_address_file()
    if token:
        from ray_tpu._private.config import GlobalConfig
        if not GlobalConfig.cluster_token:
            GlobalConfig.apply_system_config({"cluster_token": token})


def _resolve_address(address):
    _load_file_token()
    if address:
        return address
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    from ray_tpu.scripts.head_daemon import read_address_file
    addr, _token, _pid = read_address_file()
    if addr:
        return addr
    raise click.ClickException(
        "No running cluster found: pass --address, set RAY_TPU_ADDRESS, "
        "or run `ray-tpu start --head` first.")


def _head_client(address):
    from ray_tpu.runtime.rpc import RpcClient
    return RpcClient(_resolve_address(address), timeout=30)


@click.group()
def cli():
    """TPU-native distributed runtime CLI."""


@cli.command()
@click.option("--head", is_flag=True, help="Start a head node here.")
@click.option("--address", default=None,
              help="Join an existing head (starts one more worker).")
@click.option("--num-workers", default=2, show_default=True)
@click.option("--resources", default='{"CPU": 2}', show_default=True,
              help="Per-worker resources as JSON.")
@click.option("--store-capacity", default=256 * 1024 * 1024,
              show_default=True)
@click.option("--block", is_flag=True,
              help="Run the head in the foreground.")
def start(head, address, num_workers, resources, store_capacity, block):
    """Start a head daemon or add a worker to a running head."""
    if head and address:
        raise click.ClickException("--head and --address are exclusive")
    if not head and not address and not os.path.exists(
            address_file_path()):
        raise click.ClickException("Pass --head to start a new cluster")
    if head:
        cmd = [sys.executable, "-m", "ray_tpu.scripts.head_daemon",
               "--num-workers", str(num_workers),
               "--resources", resources,
               "--store-capacity", str(store_capacity)]
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        if block:
            os.execve(sys.executable, [sys.executable] + cmd[1:], env)
        proc = subprocess.Popen(cmd, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT,
                                start_new_session=True, text=True)
        deadline = time.time() + 60
        addr = None
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("RAY_TPU_HEAD_ADDRESS="):
                addr = line.strip().split("=", 1)[1]
                break
            if proc.poll() is not None:
                raise click.ClickException(
                    f"Head daemon exited: {line}")
        if addr is None:
            proc.terminate()
            raise click.ClickException("Head daemon did not report an "
                                       "address within 60s")
        click.echo(f"Started head at {addr} (pid {proc.pid}).")
        click.echo(f"Connect with ray_tpu.init(address={addr!r}) or "
                   f"RAY_TPU_ADDRESS={addr}")
    else:
        client = _head_client(address)
        wid = client.call("request_worker", json.loads(resources))
        click.echo(f"Started worker {wid}")


@cli.command()
@click.option("--address", default=None)
def stop(address):
    """Stop the running cluster."""
    from ray_tpu.scripts.head_daemon import read_address_file
    file_addr, _token, pid = read_address_file()
    # The pid/file belong to the LOCAL daemon: only touch them when
    # that is the cluster being stopped (no explicit --address, or an
    # --address matching the file), never when stopping a remote one.
    local_target = address is None or address == file_addr
    try:
        client = _head_client(address)
        client.call("shutdown", timeout=5)
    except Exception:
        pass
    # The daemon wrapper outlives the head's RPC shutdown: signal it
    # so the process tree actually exits (it removes the address file
    # itself on the way out).
    if local_target and pid:
        import signal as _signal
        try:
            os.kill(pid, _signal.SIGTERM)
            for _ in range(50):
                try:
                    os.kill(pid, 0)
                except OSError:
                    break
                time.sleep(0.1)
        except OSError:
            pass
    if local_target:
        path = address_file_path()
        if os.path.exists(path):
            os.remove(path)
    click.echo("Stopped.")


@cli.command()
@click.option("--address", default=None)
def status(address):
    """Cluster resources, workers, and jobs."""
    client = _head_client(address)
    total = client.call("cluster_resources")
    avail = client.call("available_resources")
    workers = client.call("list_workers")
    click.echo("Resources:")
    for k in sorted(total):
        click.echo(f"  {k}: {avail.get(k, 0.0):g}/{total[k]:g} free")
    click.echo(f"Workers ({len(workers)}):")
    for w in workers:
        state = "ALIVE" if w["alive"] else "DEAD"
        click.echo(f"  {w['worker_id']}: {state} "
                   f"{w['resources']} running={len(w['running_tasks'])}")
    try:
        jobs = client.call("list_jobs")
        if jobs:
            click.echo(f"Jobs ({len(jobs)}):")
            for j in jobs:
                click.echo(f"  {j['job_id']}: {j['status']} "
                           f"({j['entrypoint']!r})")
    except Exception:
        pass


@cli.command()
@click.option("--address", default=None)
@click.option("--working-dir", default=None)
@click.option("--submission-id", default=None)
@click.option("--no-wait", is_flag=True)
@click.argument("entrypoint", nargs=-1, required=True)
def submit(address, working_dir, submission_id, no_wait, entrypoint):
    """Submit a job: ray-tpu submit -- python my_script.py"""
    from ray_tpu.job import JobSubmissionClient
    addr = _resolve_address(address)
    client = JobSubmissionClient(addr)
    import shlex
    runtime_env = {"working_dir": working_dir} if working_dir else None
    job_id = client.submit_job(entrypoint=shlex.join(entrypoint),
                               submission_id=submission_id,
                               runtime_env=runtime_env)
    click.echo(f"Submitted {job_id}")
    if no_wait:
        return
    status_ = client.wait_until_finished(job_id, timeout=3600)
    click.echo(client.get_job_logs(job_id), nl=False)
    click.echo(f"Job {job_id}: {status_}")
    if status_ != "SUCCEEDED":
        sys.exit(1)


@cli.command()
@click.option("--address", default=None)
@click.argument("job_id")
def logs(address, job_id):
    """Print a job's captured output."""
    from ray_tpu.job import JobSubmissionClient
    client = JobSubmissionClient(_resolve_address(address))
    click.echo(client.get_job_logs(job_id), nl=False)


@cli.command()
@click.option("--address", default=None)
def memory(address):
    """Object-store usage (reference: `ray memory`)."""
    client = _head_client(address)
    stats = client.call("store_stats")
    click.echo(json.dumps(stats, indent=2))


@cli.command()
@click.option("--address", default=None)
@click.option("--prometheus", is_flag=True,
              help="Prometheus text exposition instead of JSON.")
def metrics(address, prometheus):
    """Cluster-wide metrics from the native shm segment."""
    client = _head_client(address)
    if prometheus:
        click.echo(client.call("metrics_prometheus"), nl=False)
    else:
        click.echo(json.dumps(client.call("metrics_snapshot"),
                              indent=2))


@cli.command()
@click.option("--address", default=None)
@click.option("--port", default=8265, type=int)
@click.option("--host", default="127.0.0.1")
def dashboard(address, port, host):
    """Serve the dashboard UI + JSON API (reference: `ray dashboard`).
    Attaches to the cluster, then blocks."""
    import time as _time

    import ray_tpu
    ray_tpu.init(address=_resolve_address(address),
                 ignore_reinit_error=True)
    from ray_tpu.dashboard import Dashboard
    dash = Dashboard(host=host, port=port).start()
    click.echo(f"dashboard at http://{host}:{dash.port}/")
    try:
        while True:
            _time.sleep(1)
    except KeyboardInterrupt:
        dash.stop()


@cli.command("client-proxy")
@click.option("--address", default=None,
              help="head address (host:port)")
@click.option("--port", default=10001, type=int)
def client_proxy(address, port):
    """Run a ray:// client proxy next to the head so remote drivers
    can connect with init(address='ray://host:port')."""
    from ray_tpu.runtime.client_proxy import serve_forever
    serve_forever(_resolve_address(address), port, echo=click.echo)


@cli.command("list")
@click.option("--address", default=None)
@click.argument("kind",
                type=click.Choice(["actors", "workers", "jobs"]))
def list_cmd(address, kind):
    """State listing (reference: `ray list actors` state CLI)."""
    client = _head_client(address)
    rows = client.call({"actors": "list_actors",
                        "workers": "list_workers",
                        "jobs": "list_jobs"}[kind])
    click.echo(json.dumps(rows, indent=2, default=str))


@cli.command()
@click.option("--output", "-o", default="timeline.json",
              show_default=True)
def timeline(output):
    """Export the local profile timeline as a Chrome trace
    (reference: `ray timeline`)."""
    import ray_tpu
    path = ray_tpu.timeline(output)
    click.echo(f"Wrote {path}")


@cli.group("serve")
def serve_group():
    """Serve deployments from the command line (reference: the
    `serve run/status/shutdown` CLI, serve/scripts.py)."""


def _serve_attach(address, standalone_ok=False):
    """Driver attach for serve subcommands: join the running cluster.
    Only `serve run` may fall back to starting a local runtime
    (standalone_ok); status/shutdown are queries and must not spawn a
    cluster just to report there is nothing to query."""
    import ray_tpu
    try:
        addr = _resolve_address(address)
    except click.ClickException:
        if not standalone_ok:
            raise
        addr = None
    ray_tpu.init(address=addr, ignore_reinit_error=True)
    return ray_tpu


@serve_group.command("run")
@click.argument("target")
@click.option("--address", default=None)
@click.option("--host", default="127.0.0.1", show_default=True)
@click.option("--port", default=8000, show_default=True, type=int)
@click.option("--blocking/--no-blocking", default=True,
              show_default=True)
def serve_run_cmd(target, address, host, port, blocking):
    """Import TARGET (module:attr — a deployment or bound node), run
    it, and expose the HTTP proxy."""
    import importlib
    sys.path.insert(0, os.getcwd())
    mod_name, _, attr = target.partition(":")
    if not attr:
        raise click.ClickException(
            f"target must be module:attr, got {target!r}")
    module = importlib.import_module(mod_name)
    try:
        app = getattr(module, attr)
    except AttributeError:
        raise click.ClickException(
            f"{mod_name!r} has no attribute {attr!r}")
    _serve_attach(address, standalone_ok=True)
    from ray_tpu import serve as serve_api
    from ray_tpu.serve.api import Deployment
    from ray_tpu.serve.http_proxy import start_http
    if isinstance(app, Deployment):
        app = app.bind()
    serve_api.run(app)
    names = sorted(serve_api.list_deployments())
    if not blocking:
        # The HTTP proxy lives in THIS process; advertising an
        # endpoint that dies on exit would be a lie. Deploy-only.
        click.echo(f"Deployed {names} (replicas stay up on the "
                   f"cluster; run without --no-blocking to serve "
                   f"HTTP, or reach them via serve handles)")
        return
    proxy = start_http(host, port)
    click.echo(f"Serving {names} at http://{host}:{proxy.port}/"
               f"<deployment>")
    click.echo("Ctrl-C to stop.")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        click.echo("Shutting down.")
        serve_api.shutdown()


@serve_group.command("status")
@click.option("--address", default=None)
def serve_status_cmd(address):
    """Deployment + replica status (reference: `serve status`)."""
    ray_tpu = _serve_attach(address)
    from ray_tpu.serve.controller import CONTROLLER_NAME
    try:
        ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        raise click.ClickException("Serve is not running here")
    from ray_tpu import serve as serve_api
    click.echo(json.dumps(serve_api.status(), indent=2, default=str))


@serve_group.command("shutdown")
@click.option("--address", default=None)
@click.option("--yes", "-y", is_flag=True,
              help="Skip the confirmation prompt.")
def serve_shutdown_cmd(address, yes):
    """Tear down all deployments (reference: `serve shutdown`)."""
    if not yes:
        click.confirm("Shut down all serve deployments?", abort=True)
    ray_tpu = _serve_attach(address)
    from ray_tpu.serve.controller import CONTROLLER_NAME
    try:
        ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        raise click.ClickException("Serve is not running here")
    from ray_tpu import serve as serve_api
    serve_api.shutdown()
    click.echo("Serve shut down.")


def main():
    cli()


if __name__ == "__main__":
    main()
