"""Head daemon: `python -m ray_tpu.scripts.head_daemon` — the process
behind `ray-tpu start --head` (reference: services.py start_gcs_server /
start_raylet spawning the native daemons; here the head + node manager
live in one process)."""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def address_file_path() -> str:
    return os.path.join("/tmp", "ray_tpu", "head_address")


def write_address_file(address: str, token: str) -> str:
    """Persist the head address + cluster token + daemon pid for
    external clients (CLI, drivers on this machine). The token is the
    cluster's RPC secret, so the file is 0600 (redis-password-file
    analogue); the pid lets `stop` terminate the daemon wrapper."""
    path = address_file_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump({"address": address, "token": token,
                   "pid": os.getpid()}, f)
    return path


def read_address_file():
    """(address, token|None, pid|None) from the address file; accepts
    the legacy plain "host:port" format (token/pid None)."""
    path = address_file_path()
    if not os.path.exists(path):
        return None, None, None
    with open(path) as f:
        raw = f.read().strip()
    if raw.startswith("{"):
        try:
            blob = json.loads(raw)
            return (blob.get("address"), blob.get("token") or None,
                    blob.get("pid"))
        except json.JSONDecodeError:
            return None, None, None
    return (raw or None), None, None


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--resources", default='{"CPU": 2}')
    parser.add_argument("--store-capacity", type=int,
                        default=256 * 1024 * 1024)
    args = parser.parse_args()

    from ray_tpu.runtime.node import NodeManager
    nm = NodeManager(num_workers=args.num_workers,
                     resources_per_worker=json.loads(args.resources),
                     store_capacity=args.store_capacity)
    nm.wait_for_workers(args.num_workers)
    from ray_tpu._private.config import GlobalConfig
    path = write_address_file(nm.head_address,
                              GlobalConfig.cluster_token)
    # stdout line parsed by the CLI parent.
    print(f"RAY_TPU_HEAD_ADDRESS={nm.head_address}", flush=True)

    stop = {"flag": False}

    def _sig(_s, _f):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        try:
            os.remove(path)
        except OSError:
            pass
        nm.stop()


if __name__ == "__main__":
    sys.exit(main())
