"""SPMD train-step construction: the compiled heart of JaxTrainer.

Replaces the reference's DDP wiring (train/torch/config.py
_setup_torch_process_group + NCCL allreduce) with mesh-sharded pjit: place
params/opt-state by sharding rules, shard the batch on the data axes, jit the
whole step with donation — XLA inserts the gradient psum over ICI/DCN and
overlaps it with the backward pass.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.mesh.sharding import ShardingRules, infer_sharding


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, optimizer: optax.GradientTransformation):
        return cls(params=params, opt_state=optimizer.init(params),
                   step=jnp.zeros((), jnp.int32))


def state_shardings(state: TrainState, rules: ShardingRules,
                    mesh: Mesh) -> TrainState:
    """Shardings for the whole state: params by rules; optimizer slots
    mirror their parameter's sharding; step replicated."""
    param_sh = infer_sharding(state.params, rules, mesh)
    # Walk the opt_state: any leaf whose shape matches a param leaf gets
    # that param's sharding (optax slots mirror params); scalars replicate.
    flat_params = {l.shape: s for l, s in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(param_sh))}
    rep = NamedSharding(mesh, P())

    def slot_sharding(leaf):
        return flat_params.get(getattr(leaf, "shape", None), rep)

    opt_sh = jax.tree_util.tree_map(slot_sharding, state.opt_state)
    return TrainState(params=param_sh, opt_state=opt_sh,
                      step=rep)


def shard_state(state: TrainState, rules: ShardingRules,
                mesh: Mesh) -> TrainState:
    sh = state_shardings(state, rules, mesh)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), state, sh)


def make_train_step(loss_fn: Callable[[Any, Any], jax.Array],
                    optimizer: optax.GradientTransformation,
                    donate: bool = True):
    """loss_fn(params, batch) -> scalar loss. Returns jitted
    (state, batch) -> (state, metrics)."""

    def step_fn(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return (TrainState(new_params, new_opt, state.step + 1),
                {"loss": loss, "grad_norm": gnorm,
                 "step": state.step + 1})

    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())


def batch_shardings(mesh: Mesh, batch_example) -> Any:
    """Shard every batch leaf on its leading dim over (dcn, data, fsdp)."""
    sh = NamedSharding(mesh, P(("dcn", "data", "fsdp")))

    def leaf_sh(x):
        return sh
    return jax.tree_util.tree_map(leaf_sh, batch_example)


def put_batch(batch, mesh: Mesh):
    """Shard a batch's leading dim over the data axes.

    Single-process: a plain device_put. Multi-process gang (the mesh
    spans jax.distributed hosts): each process contributes its LOCAL
    batch as this host's shard of the global array — per-host data
    loading, the global batch is num_hosts x local without any
    host-to-host copy."""
    sh = NamedSharding(mesh, P(("dcn", "data", "fsdp")))
    import numpy as np

    def put(x):
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                sh, np.asarray(x))
        return jax.device_put(x, sh)

    return jax.tree_util.tree_map(put, batch)
