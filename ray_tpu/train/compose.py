"""Composed parallelism: one train step over any mix of mesh axes.

SURVEY §7 step 7's obligation — pipeline (PP), sequence/context (SP),
expert (EP) and data/fsdp/dcn parallelism "composable as mesh-axis
configs on JaxTrainer" — satisfied the TPU way: ONE `shard_map` over
the full mesh runs the GPipe schedule on the `pipeline` axis while the
batch stays sharded over (dcn, data, fsdp) on its leading dim and over
`sequence` on its second dim; the stage function may freely use the
manual-collective building blocks inside (ring_attention over
`sequence`, all_to_all expert dispatch over `expert`). Gradients flow
through the whole composition — `jax.value_and_grad` of the
shard_mapped loss inserts the psums for replicated params and the
transposed ppermutes for the pipeline/ring exchanges.

The reference has no counterpart (its only scaling axis is data
parallelism; SURVEY.md §2.4); this module is pure TPU-native surface.

Usage with JaxTrainer (the loop runs identically on 1 process or a
multi-host gang — the mesh comes from ScalingConfig.mesh):

    def loop(config):
        mesh = session.get_mesh()
        step, state = make_composed_train_step(
            stage_fn, loss_fn, optax.adam(1e-3), mesh,
            stage_params, num_microbatches=4)
        for batch in data:
            state, metrics = step(state, put_composed_batch(batch, mesh))
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.pipeline import pipeline_run_local
from ray_tpu.train.spmd import TrainState

# Batch layout: leading dim sharded over the data-parallel axes,
# second dim (sequence) over the sequence axis.
DATA_AXES = ("dcn", "data", "fsdp")


def composed_batch_spec(ndim: int) -> P:
    """PartitionSpec for a batch leaf: [B, T, ...] -> data axes on B,
    sequence on T; 1-D leaves shard only the batch dim."""
    if ndim == 0:
        return P()              # scalars replicate
    if ndim == 1:
        return P(DATA_AXES)
    return P(DATA_AXES, "sequence")


def put_composed_batch(batch, mesh: Mesh):
    """Device-place a batch pytree with the composed layout. On a
    multi-host gang each process contributes its local shard (per-host
    data loading, same contract as spmd.put_batch)."""
    import numpy as np

    def put(x):
        x = np.asarray(x)
        sh = NamedSharding(mesh, composed_batch_spec(x.ndim))
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sh, x)
        return jax.device_put(x, sh)

    return jax.tree_util.tree_map(put, batch)


def shard_stage_params(stage_params, mesh: Mesh):
    """Place stage-stacked params (leading stage axis) P('pipeline')."""
    sh = NamedSharding(mesh, P("pipeline"))
    return jax.tree_util.tree_map(
        lambda p: jax.device_put(p, sh), stage_params)


def make_composed_loss(stage_fn: Callable[[Any, jax.Array], jax.Array],
                       loss_fn: Callable[[jax.Array, Any],
                                         Tuple[jax.Array, jax.Array]],
                       mesh: Mesh,
                       num_microbatches: int = 1):
    """Build loss(params, batch) running the full composition.

    stage_fn(stage_params, x_local) -> activation (same shape): one
        pipeline stage's computation on this device's LOCAL slice
        ([B/(dp), T/(sp), ...]). May use ring_attention(axis_name=
        'sequence'), lax collectives over 'expert'/'tensor', etc.
    loss_fn(out_local, batch_local) -> (loss_sum, weight): LOCAL sums;
        the builder psums them over the whole mesh and returns
        sum/weight (a true global mean regardless of sharding).
    batch: pytree whose first leaf is the input x; the entire batch
        pytree is passed to loss_fn.
    """
    S = mesh.shape.get("pipeline", 1)
    M = num_microbatches
    all_axes = tuple(mesh.axis_names)

    def loss(params, batch):
        params_spec = jax.tree_util.tree_map(
            lambda _: P("pipeline"), params)
        batch_spec = jax.tree_util.tree_map(
            lambda b: composed_batch_spec(b.ndim), batch)

        @partial(jax.shard_map, mesh=mesh,
                 in_specs=(params_spec, batch_spec), out_specs=P())
        def run(local_params, local_batch):
            # Each pipeline rank holds S_total/S stages; apply them in
            # order (a "stage" of the schedule = this rank's slice).
            def local_stage(lp, act):
                def body(carry, p):
                    return stage_fn(p, carry), None
                out, _ = jax.lax.scan(body, act, lp)
                return out

            xl = jax.tree_util.tree_leaves(local_batch)[0]
            # The stage's output may vary over ANY nontrivial mesh
            # axis (pipeline-sharded params, expert all_to_alls,
            # tensor collectives inside stage_fn) — type the input,
            # hence every schedule carry derived from it, as varying
            # over all of them up front or the scan vma types diverge
            # on the first iteration. Over-marking is semantically
            # safe (it only widens the loss psum, which the weight
            # widens identically).
            vma = set(getattr(jax.typeof(xl), "vma", ()) or ())
            widen = tuple(a for a in mesh.axis_names
                          if mesh.shape[a] > 1 and a not in vma)
            if widen:
                xl = jax.lax.pcast(xl, widen, to="varying")
            if S > 1:
                out = pipeline_run_local(local_stage, local_params,
                                         xl, M, S, "pipeline")
            else:
                out = local_stage(local_params, xl)
            lsum, weight = loss_fn(out, local_batch)
            lsum = jnp.asarray(lsum, jnp.float32)
            weight = jnp.asarray(weight, jnp.float32)
            # Global mean: the loss sum is psum'd over exactly the
            # axes it VARIES over (batch/sequence shards; replicated
            # pipeline/tensor copies already hold the full value and
            # jax's vma typing rejects psum over invarying axes). The
            # weight is by contract a LOCAL count, so along any sum
            # axis where it came out invarying (e.g. a shape-derived
            # Python constant) the replicas each hold the local count
            # and a multiply stands in for the psum.
            vma_l = set(getattr(jax.typeof(lsum), "vma", ()) or ())
            sum_axes = tuple(a for a in all_axes if a in vma_l)
            if sum_axes:
                lsum = jax.lax.psum(lsum, sum_axes)
            vma_w = set(getattr(jax.typeof(weight), "vma", ()) or ())
            w_axes = tuple(a for a in sum_axes if a in vma_w)
            if w_axes:
                weight = jax.lax.psum(weight, w_axes)
            for a in sum_axes:
                if a not in vma_w:
                    weight = weight * mesh.shape[a]
            return lsum / weight

        return run(params, batch)

    return loss


def make_composed_train_step(
        stage_fn, loss_fn, optimizer: optax.GradientTransformation,
        mesh: Mesh, stage_params, num_microbatches: int = 1,
        donate: bool = True):
    """The composed analogue of spmd.make_train_step: returns
    (jitted_step, initial TrainState) where the step trains through
    pipeline x sequence x data/fsdp/dcn (x whatever the stage_fn uses
    internally) in ONE compiled program."""
    stage_params = shard_stage_params(stage_params, mesh)
    state = TrainState.create(stage_params, optimizer)
    composed = make_composed_loss(stage_fn, loss_fn, mesh,
                                  num_microbatches)

    def step_fn(state: TrainState, batch):
        loss, grads = jax.value_and_grad(composed)(state.params, batch)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (TrainState(new_params, new_opt, state.step + 1),
                {"loss": loss, "step": state.step + 1})

    return (jax.jit(step_fn, donate_argnums=(0,) if donate else ()),
            state)
