"""Multi-host gang bootstrap: jax.distributed across a trainer gang.

Reference analogue: the torch rendezvous in
python/ray/train/torch/config.py:54 (_setup_torch_process_group) — worker 0
owns the rendezvous endpoint and every gang member connects to it. The
TPU-native replacement is jax.distributed's coordinator service: after
``init_gang`` on every member, ``jax.devices()`` is the GLOBAL device list
across all gang hosts and pjit programs span the whole slice, with XLA
placing collectives on ICI (intra-slice) / DCN (cross-slice). No process
groups, no NCCL — the mesh IS the collective topology (SURVEY.md §5.8).

One gang member == one OS process == one "JAX host". On real TPU pods
that's one TPU VM host (its local chips are the process's addressable
devices); in tests it's a worker process with a virtual CPU device count.
"""
from __future__ import annotations

import logging
import socket
from typing import Optional

logger = logging.getLogger(__name__)

# Collision-free identity for "distinct OS process" checks: PIDs repeat
# across hosts/containers, hostnames repeat across containers — a
# per-process random id does not.
import uuid as _uuid  # noqa: E402
PROCESS_UUID = _uuid.uuid4().hex

# Per-process gang state. jax.distributed can only be initialized once per
# process lifetime; re-bootstrap therefore requires a fresh worker process
# (the node manager replaces dead workers, so elastic restart gets fresh
# processes for the dead members; surviving members re-use their init only
# if the coordinator endpoint is unchanged).
_STATE = {"coordinator": None, "num_processes": 0, "process_id": -1}


def pick_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def host_ip() -> str:
    """Best-effort routable IP of this host (the coordinator must be
    reachable from every gang member's host)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))     # no packets sent
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def coordinator_endpoint() -> str:
    """Allocate a coordinator endpoint on THIS host. Must be called in
    the process that will be gang process 0 (jax.distributed starts the
    coordination service there). If this process already bootstrapped as
    process 0, returns the existing endpoint so a gang re-run in
    surviving processes is an idempotent no-op."""
    if gang_initialized() and _STATE["process_id"] == 0:
        return _STATE["coordinator"]
    return f"{host_ip()}:{pick_free_port()}"


def init_gang(coordinator: str, num_processes: int,
              process_id: int) -> None:
    """Idempotent jax.distributed.initialize for this process.

    Must run before this process's first JAX computation (backend init
    locks the device topology). A second call with the same coordinates
    is a no-op; different coordinates in an already-bootstrapped process
    raise — the caller needs a fresh process.
    """
    if _STATE["coordinator"] is not None:
        if (_STATE["coordinator"] == coordinator and
                _STATE["num_processes"] == num_processes and
                _STATE["process_id"] == process_id):
            return
        raise RuntimeError(
            f"jax.distributed already initialized in this process as "
            f"process {_STATE['process_id']}/{_STATE['num_processes']} "
            f"@ {_STATE['coordinator']}; cannot re-bootstrap as "
            f"{process_id}/{num_processes} @ {coordinator}. Gang "
            f"re-bootstrap requires a fresh worker process.")
    import jax
    jax.distributed.initialize(coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _STATE.update(coordinator=coordinator, num_processes=num_processes,
                  process_id=process_id)
    logger.info("gang member %d/%d joined %s: %d global / %d local "
                "devices", process_id, num_processes, coordinator,
                jax.device_count(), jax.local_device_count())


def gang_initialized() -> bool:
    return _STATE["coordinator"] is not None


def gang_process_id() -> Optional[int]:
    return _STATE["process_id"] if gang_initialized() else None
