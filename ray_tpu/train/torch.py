"""Torch backend: gloo process groups over the worker gang.

Capability parity with the reference's torch Train backend
(python/ray/train/torch/config.py:28,54,105 — `_TorchBackend.on_start`
runs `_setup_torch_process_group` on every worker with a TCP rendezvous
on worker 0; `prepare_model` wraps the model in DDP). TPU-native stance:
JaxTrainer + mesh collectives are the flagship path; TorchTrainer
exists for CPU torch workloads and API parity. Requires gang members in
distinct processes (use the multiprocess runtime with SPREAD placement);
one process can host only one torch process-group rank.
"""
from __future__ import annotations

import socket
from typing import Callable, Dict, Optional

from ray_tpu.train.trainer import BaseTrainer

_RDZV_KEY = "_torch_init_method"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _setup_torch_process_group(rank: int, world_size: int,
                               config: Dict) -> None:
    """Runs on each gang member (reference: train/torch/config.py:54)."""
    import torch.distributed as dist
    if world_size <= 1:
        return
    if dist.is_initialized():
        dist.destroy_process_group()
    dist.init_process_group(
        backend="gloo",
        init_method=config[_RDZV_KEY],
        rank=rank,
        world_size=world_size)


def prepare_model(model):
    """Wrap in DDP when a process group is active (reference:
    train/torch/train_loop_utils.py prepare_model)."""
    import torch.distributed as dist
    if dist.is_available() and dist.is_initialized() and \
            dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel
        return DistributedDataParallel(model)
    return model


def get_device():
    import torch
    return torch.device("cpu")


class TorchTrainer(BaseTrainer):
    """Data-parallel torch training on a gang of worker actors with a
    gloo process group (NCCL has no role on TPU hosts)."""

    def __init__(self, train_loop_per_worker: Callable, **kwargs):
        super().__init__(train_loop_per_worker, **kwargs)
        # TCP rendezvous chosen up front so every gang member gets the
        # same init_method through the loop config.
        self._config[_RDZV_KEY] = \
            f"tcp://127.0.0.1:{_free_port()}"

    def _backend_setup(self) -> Optional[Callable]:
        return _setup_torch_process_group
