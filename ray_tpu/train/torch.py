"""Torch backend: gloo process groups over the worker gang.

Capability parity with the reference's torch Train backend
(python/ray/train/torch/config.py:28,54,105 — `_TorchBackend.on_start`
runs `_setup_torch_process_group` on every worker with a TCP rendezvous
on worker 0; train_loop_utils.py — `prepare_model` wraps DDP,
`prepare_data_loader` installs a DistributedSampler, checkpoints carry
module state dicts). TPU-native stance: JaxTrainer + mesh collectives
are the flagship path; TorchTrainer exists for CPU torch workloads and
API parity. Requires gang members in distinct processes (use the
multiprocess runtime with SPREAD placement); one process can host only
one torch process-group rank.
"""
from __future__ import annotations

import dataclasses
import socket
from typing import Callable, Dict, Optional

from ray_tpu.train.trainer import BaseTrainer

_RDZV_KEY = "_torch_init_method"


@dataclasses.dataclass
class TorchConfig:
    """Backend knobs (reference: train/torch/config.py:28 TorchConfig).
    `backend` defaults to gloo — the only sane choice on TPU hosts
    (NCCL needs NVIDIA GPUs); `init_method` tcp|env mirrors the
    reference; `timeout_s` bounds the rendezvous."""

    backend: str = "gloo"
    init_method: str = "tcp"        # "tcp" | "env"
    timeout_s: float = 1800.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _setup_torch_process_group(rank: int, world_size: int,
                               config: Dict) -> None:
    """Runs on each gang member (reference: train/torch/config.py:54)."""
    import datetime
    import torch.distributed as dist
    if world_size <= 1:
        return
    if dist.is_initialized():
        dist.destroy_process_group()
    tc: TorchConfig = config.get("_torch_config") or TorchConfig()
    if tc.init_method == "env":
        # env:// rendezvous (reference: TorchConfig init_method="env"):
        # expose the chosen endpoint through the standard variables.
        import os
        addr = config[_RDZV_KEY][len("tcp://"):]
        host, _, port = addr.rpartition(":")
        os.environ["MASTER_ADDR"] = host
        os.environ["MASTER_PORT"] = port
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world_size)
        init_method = "env://"
    elif tc.init_method == "tcp":
        init_method = config[_RDZV_KEY]
    else:
        raise ValueError(
            f"TorchConfig.init_method must be 'tcp' or 'env', got "
            f"{tc.init_method!r}")
    dist.init_process_group(
        backend=tc.backend,
        init_method=init_method,
        rank=rank,
        world_size=world_size,
        timeout=datetime.timedelta(seconds=tc.timeout_s))


def prepare_model(model):
    """Wrap in DDP when a process group is active (reference:
    train/torch/train_loop_utils.py prepare_model)."""
    import torch.distributed as dist
    if dist.is_available() and dist.is_initialized() and \
            dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Shard a DataLoader across the gang (reference:
    train_loop_utils.prepare_data_loader): rebuilds it with a
    DistributedSampler over the active process group so each rank sees
    its 1/world_size of the dataset. No-op outside a gang."""
    import torch.distributed as dist
    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    from torch.utils.data import DataLoader, RandomSampler
    from torch.utils.data.distributed import DistributedSampler
    # Preserve the loader's order semantics: only loaders that were
    # shuffling (RandomSampler) keep shuffling under the distributed
    # sampler — a sequential eval loader must stay sequential.
    was_shuffling = isinstance(getattr(data_loader, "sampler", None),
                               RandomSampler)
    sampler = DistributedSampler(data_loader.dataset,
                                 num_replicas=dist.get_world_size(),
                                 rank=dist.get_rank(),
                                 shuffle=was_shuffling)
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last)


def get_device():
    import torch
    return torch.device("cpu")


def checkpoint_from_model(model, **extra) -> "Checkpoint":
    """Module -> AIR Checkpoint (state dict unwrapped from DDP), the
    shape TorchTrainer results carry (reference:
    train/torch/torch_checkpoint.py)."""
    from ray_tpu.air import Checkpoint
    module = getattr(model, "module", model)    # unwrap DDP
    return Checkpoint.from_dict(
        {"model_state": {k: v.detach().cpu()
                         for k, v in module.state_dict().items()},
         **extra})


def load_model_from_checkpoint(checkpoint, model):
    """Restore a module's weights from a TorchTrainer checkpoint."""
    state = checkpoint.to_dict()["model_state"]
    module = getattr(model, "module", model)
    module.load_state_dict(state)
    return model


class TorchTrainer(BaseTrainer):
    """Data-parallel torch training on a gang of worker actors with a
    gloo process group (NCCL has no role on TPU hosts)."""

    def __init__(self, train_loop_per_worker: Callable,
                 torch_config: Optional[TorchConfig] = None, **kwargs):
        super().__init__(train_loop_per_worker, **kwargs)
        # TCP rendezvous chosen up front so every gang member gets the
        # same init_method through the loop config.
        self._config[_RDZV_KEY] = \
            f"tcp://127.0.0.1:{_free_port()}"
        self._config["_torch_config"] = torch_config or TorchConfig()

    def _backend_setup(self) -> Optional[Callable]:
        return _setup_torch_process_group
