"""Predictors: checkpoint -> inference, single and batch.

Capability parity with the reference's Predictor/BatchPredictor
(python/ray/train/predictor.py, batch_predictor.py — from_checkpoint
construction, predict over a Dataset with task- or actor-pool compute).
TPU-native: JaxPredictor holds jitted apply over device params; batch
prediction rides data.map_batches with actor compute so model state
loads once per actor (the reference's actor-pool pattern).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs
                        ) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch):
        raise NotImplementedError


class JaxPredictor(Predictor):
    """apply_fn(params, batch) jitted once; params from checkpoint."""

    def __init__(self, params, apply_fn: Callable):
        import jax
        self._params = params
        self._apply = jax.jit(apply_fn)

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable,
                        params_key: str = "params") -> "JaxPredictor":
        data = checkpoint.to_dict()
        return cls(data[params_key], apply_fn)

    def predict(self, batch):
        import jax.numpy as jnp
        return np.asarray(self._apply(self._params, jnp.asarray(batch)))


class SklearnPredictor(Predictor):
    def __init__(self, estimator):
        self._est = estimator

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **_) -> "SklearnPredictor":
        return cls(checkpoint.to_dict()["estimator"])

    def predict(self, batch):
        return self._est.predict(np.asarray(batch))


class BatchPredictor:
    """Distributed inference over a Dataset (reference:
    train/batch_predictor.py)."""

    def __init__(self, checkpoint: Checkpoint, predictor_cls,
                 **predictor_kwargs):
        self._checkpoint = checkpoint
        self._cls = predictor_cls
        self._kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, predictor_cls,
                        **kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **kwargs)

    def predict(self, dataset, *, batch_size: int = 256,
                compute: str = "actors", num_actors: int = 2,
                feature_key: Optional[str] = None):
        """Returns a Dataset of {'prediction': ...} rows."""
        ckpt, pred_cls, kwargs = self._checkpoint, self._cls, self._kwargs

        class _PredictorHolder:
            def __init__(self):
                self.predictor = pred_cls.from_checkpoint(ckpt, **kwargs)

            def __call__(self, batch):
                arr = _extract(batch, feature_key)
                out = self.predictor.predict(arr)
                return [{"prediction": p} for p in np.asarray(out)]

        def _extract(batch, key):
            rows = list(batch)
            if key is not None:
                return np.stack([np.asarray(r[key]) for r in rows])
            if rows and isinstance(rows[0], dict):
                raise ValueError(
                    "dict rows need feature_key= to select the input")
            return np.stack([np.asarray(r) for r in rows])

        if compute == "actors":
            return dataset.map_batches(
                None, batch_size=batch_size, compute="actors",
                num_actors=num_actors,
                fn_constructor=_PredictorHolder)

        holder = _PredictorHolder()
        return dataset.map_batches(holder, batch_size=batch_size)
