"""HuggingFace transformers integration for the train gang.

Capability parity with the reference's HuggingFaceTrainer
(python/ray/train/huggingface/huggingface_trainer.py — a
DataParallelTrainer whose workers each build a transformers.Trainer
via trainer_init_per_worker, train under the torch process group, and
stream HF logs back as session reports; train/huggingface/_huggingface
_utils.py's TrainReportCallback). Same shape here on the gloo
TorchTrainer gang: rank 0's logs become session.report()s and the
final report carries the model state as an AIR Checkpoint.
"""
from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.train.torch import TorchTrainer, checkpoint_from_model


def _report_callback():
    """transformers.TrainerCallback streaming HF log events into
    session.report (rank 0 only — one report stream per gang, like
    the reference's TrainReportCallback)."""
    import transformers

    from ray_tpu.air import session

    class _Report(transformers.TrainerCallback):
        def on_log(self, args, state, control, logs=None, **kw):
            if not state.is_world_process_zero or not logs:
                return
            metrics = {k: v for k, v in logs.items()
                       if isinstance(v, (int, float))}
            metrics["step"] = state.global_step
            metrics["epoch"] = float(state.epoch or 0.0)
            session.report(metrics)

    return _Report()


class HuggingFaceTrainer(TorchTrainer):
    """Distributed transformers.Trainer over the worker gang.

    ``trainer_init_per_worker(config) -> transformers.Trainer`` runs
    on every gang member AFTER the torch process group is up, so the
    Trainer's accelerate state adopts the gloo group and gradients
    sync across workers; per-rank data sharding is the HF Trainer's
    own DistributedSampler behavior.
    """

    def __init__(self, trainer_init_per_worker: Callable,
                 **kwargs):
        def train_loop(config):
            import os

            import torch.distributed as dist

            from ray_tpu.air import session

            env_keys = ("MASTER_ADDR", "MASTER_PORT", "RANK",
                        "WORLD_SIZE", "LOCAL_RANK",
                        "ACCELERATE_USE_CPU")
            saved = {k: os.environ.get(k) for k in env_keys}
            if dist.is_available() and dist.is_initialized() and \
                    dist.get_world_size() > 1:
                # accelerate discovers distributed state from the
                # environment, not from the live process group: hand
                # it THIS gang's coordinates (MASTER_* from this
                # fit's TCP rendezvous — never a previous fit's).
                from ray_tpu.train.torch import _RDZV_KEY
                rdzv = config.get(_RDZV_KEY, "")
                if rdzv.startswith("tcp://"):
                    host, _, port = rdzv[len("tcp://"):].rpartition(":")
                    os.environ["MASTER_ADDR"] = host
                    os.environ["MASTER_PORT"] = port
                os.environ["RANK"] = str(dist.get_rank())
                os.environ["WORLD_SIZE"] = str(dist.get_world_size())
                os.environ["LOCAL_RANK"] = str(dist.get_rank())
                os.environ["ACCELERATE_USE_CPU"] = "true"
            try:
                trainer = trainer_init_per_worker(config)
                trainer.add_callback(_report_callback())
                result = trainer.train()
                final = {"train_loss": float(result.training_loss),
                         "global_step":
                             int(trainer.state.global_step),
                         "world_size": int(trainer.args.world_size)}
                is_zero = trainer.state.is_world_process_zero
                ckpt = checkpoint_from_model(trainer.model) \
                    if is_zero else None
                session.report(final, checkpoint=ckpt)
            finally:
                # Worker processes outlive this fit: stale RANK/
                # WORLD_SIZE/MASTER_* would make accelerate in a LATER
                # workload rendezvous against a dead port.
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v

        super().__init__(train_loop, **kwargs)
