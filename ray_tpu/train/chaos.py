"""Seeded chaos for elastic training: schedules, injection, gating.

The trainer's preemption-tolerance story (durable async checkpoints +
heartbeat gang supervision + elastic restart, see train/trainer.py and
air/checkpoint_manager.py) is only worth anything if it survives an
adversarial run — this module is the proof harness, the training
analogue of the serving layer's fault seam (serve/faults.py).

Three pieces:

- ``ChaosEvent`` / ``make_schedule(seed, ...)`` — a deterministic
  schedule of faults keyed to training STEPS (not wall time, so runs
  are reproducible across machine speeds). A schedule always carries
  at least one of every requested kind:

  ============  =====================================================
  kind          what fires
  ============  =====================================================
  ``kill``      hard actor kill of one gang member (host crash)
  ``hang``      one member wedges — alive, answering polls, making
                zero progress (the failure mode only a heartbeat
                deadline catches)
  ``preempt``   a TPU slice gets a preemption notice with a real
                grace window, then vanishes; capacity stays stocked
                out for a while (SimulatedTPUCloud.preempt)
  ``torn_ckpt`` a torn checkpoint directory appears at a step NEWER
                than the last durable commit (the litter a
                non-atomic writer leaves when the plug is pulled),
                then the gang is crashed — resume must skip it
  ============  =====================================================

- ``ChaosInjector`` — a driver-side watcher thread that observes the
  live trainer (``last_seen_step`` / ``restarts``) and fires events
  when the run reaches their step.
- worker-side gates (``check_generation`` / ``hang_gate``) the chaos
  train loop calls each step. The GENERATION file solves the zombie
  problem of an in-process runtime: ``ray_tpu.kill`` marks an actor
  dead but cannot stop its running thread, so a superseded loop must
  stop ITSELF. The file holds the newest STARTED attempt id (the
  trainer-assigned, monotonic ``session.get_attempt()`` token): every
  gang fences its own attempt at loop start, the injector fences
  ``restarts + 1`` just before it kills (so the victim's thread stops
  within one step even before the replacement boots), and any loop
  whose attempt is older than the file's raises ``StaleGeneration``
  (its CheckpointManager pre-commit hook checks the same token, so a
  zombie can never commit a checkpoint either). Fencing on the
  trainer's own attempt counter — not an injector-side bump — is what
  makes this race-free: a freshly launched gang can never observe a
  token newer than its own.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

KINDS = ("kill", "hang", "preempt", "torn_ckpt")

GEN_FILE = "GENERATION"


class StaleGeneration(RuntimeError):
    """Raised by a superseded train loop (its gang was torn down and a
    newer attempt owns the run). Never reaches the trainer of the NEW
    attempt — the raising actor is already dead to it."""


class HangReleased(RuntimeError):
    """Raised by a formerly-wedged loop once its hang file is removed
    (the gang it belonged to is long gone; the loop must not resume)."""


@dataclasses.dataclass
class ChaosEvent:
    """One planned fault. Fires when the trainer's last reported step
    reaches ``at_step``."""
    kind: str
    at_step: int
    rank: int = 1                  # kill/hang target (clamped to gang)
    grace_s: float = 2.0           # preempt: notice -> slice death
    stockout_s: float = 0.5        # preempt: READY promotions blocked
    fired: bool = False
    fired_at_step: Optional[int] = None

    def as_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "at_step": self.at_step,
                "rank": self.rank, "grace_s": self.grace_s,
                "stockout_s": self.stockout_s, "fired": self.fired,
                "fired_at_step": self.fired_at_step}


def make_schedule(seed: int, steps_total: int, checkpoint_interval: int,
                  kinds=KINDS, extra: int = 0,
                  grace_s: float = 2.0,
                  stockout_s: float = 0.5) -> List[ChaosEvent]:
    """Deterministic schedule: ≥1 event of every kind in ``kinds``
    plus ``extra`` more, spaced at least one checkpoint interval
    apart inside (interval, steps_total - 2*interval] so no event
    fires before the first durable commit or too close to the end to
    observe recovery. Same seed ⇒ identical schedule."""
    if checkpoint_interval < 1:
        raise ValueError("checkpoint_interval must be >= 1")
    n = len(kinds) + extra
    lo = checkpoint_interval + 1
    hi = steps_total - 2 * checkpoint_interval
    if hi - lo < n * checkpoint_interval:
        raise ValueError(
            f"steps_total={steps_total} too small for {n} events "
            f"spaced {checkpoint_interval} apart in [{lo}, {hi})")
    rng = random.Random(seed)
    ordered = list(kinds) + [rng.choice(list(kinds))
                             for _ in range(extra)]
    rng.shuffle(ordered)
    span = (hi - lo) // n
    events = []
    for i, kind in enumerate(ordered):
        base = lo + i * span
        jitter = rng.randrange(max(1, span - checkpoint_interval))
        events.append(ChaosEvent(
            kind=kind, at_step=base + jitter,
            rank=rng.randint(0, 3),
            grace_s=grace_s, stockout_s=stockout_s))
    return events


# ---------------------------------------------------------------------------
# Worker-side gates (called from inside the chaos train loop)
# ---------------------------------------------------------------------------

# In-process bookkeeping shared by every gang generation (the local
# runtime hosts all actors in one process): hang tickets already
# consumed, and each attempt's resume step (rank 0 appends at loop
# start — the lost-progress measurement's ground truth).
_consumed_lock = threading.Lock()
_consumed_hangs: set = set()
RESUMES: List[int] = []


def reset_measurements() -> None:
    """Clear cross-run module state (call per harness run/test)."""
    with _consumed_lock:
        _consumed_hangs.clear()
    del RESUMES[:]


def generation(control_dir: str) -> int:
    """The newest attempt id known to have started (0 when none)."""
    try:
        with open(os.path.join(control_dir, GEN_FILE)) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def fence(control_dir: str, attempt: int) -> int:
    """Record that ``attempt`` has started: advance the generation
    file to it (monotonic — an older writer can never move it back).
    Returns the resulting generation."""
    path = os.path.join(control_dir, GEN_FILE)
    cur = generation(control_dir)
    if attempt <= cur:
        return cur
    tmp = f"{path}.tmp-{uuid.uuid4().hex}"
    with open(tmp, "w") as f:
        f.write(str(attempt))
    os.replace(tmp, path)
    return attempt


def check_generation(control_dir: str, attempt: int) -> None:
    """Raise ``StaleGeneration`` when a newer attempt has started —
    this loop's gang was torn down and it must stop itself. Called
    every step AND from the CheckpointManager pre-commit hook (a
    zombie may not commit, ever)."""
    if control_dir and generation(control_dir) > attempt:
        raise StaleGeneration(
            f"gang attempt {attempt} superseded by attempt "
            f"{generation(control_dir)}")


class AttemptFence:
    """Context-manager packaging of the GENERATION-file fence for loops
    outside the trainer (e.g. the RLHF driver in ray_tpu.rl): entering
    records this attempt as started (``fence``); ``check()`` is the
    per-round / pre-commit gate and raises ``StaleGeneration`` once a
    newer attempt has fenced — a superseded loop stops itself and can
    never commit, the same zombie discipline as the chaos train loop."""

    def __init__(self, control_dir: str, attempt: int):
        self.control_dir = control_dir
        self.attempt = attempt

    def __enter__(self) -> "AttemptFence":
        os.makedirs(self.control_dir, exist_ok=True)
        fence(self.control_dir, self.attempt)
        return self

    def check(self) -> None:
        check_generation(self.control_dir, self.attempt)

    def __exit__(self, *exc) -> bool:
        return False


def _hang_path(control_dir: str, rank: int) -> str:
    return os.path.join(control_dir, f"hang-{rank}")


def hang_gate(control_dir: str, rank: int) -> None:
    """Wedge this worker while its hang file exists: no heartbeat, no
    reports, but the actor keeps answering polls — progress death,
    not liveness death. Each hang file is a one-shot ticket (consumed
    in-process) so the replacement gang doesn't re-wedge on the same
    file; once the injector removes the file the wedged loop raises
    instead of resuming — it belongs to a dead gang."""
    if not control_dir:
        return
    path = _hang_path(control_dir, rank)
    if not os.path.exists(path):
        return
    try:
        with open(path) as f:
            ticket = f.read().strip()
    except OSError:
        return
    with _consumed_lock:
        if ticket in _consumed_hangs:
            return
        _consumed_hangs.add(ticket)
    while os.path.exists(path):
        time.sleep(0.02)
    raise HangReleased(f"rank {rank} released from hang {ticket}")


# ---------------------------------------------------------------------------
# Driver-side injector
# ---------------------------------------------------------------------------


class ChaosInjector:
    """Watcher thread firing a schedule against a live trainer.

    Needs the trainer (step/restart observability + the active gang),
    the control dir the loop's gates watch, the checkpoint root (torn
    injection), and — for preemption events — the SimulatedTPUCloud
    plus the queued-resource names backing the gang's slices.
    """

    def __init__(self, trainer, schedule: List[ChaosEvent],
                 control_dir: str, ckpt_root: str,
                 checkpoint_interval: int,
                 cloud=None, slices: Optional[List[str]] = None,
                 accelerator_type: str = "v5e-1",
                 backfill: bool = True,
                 poll_s: float = 0.01):
        self.trainer = trainer
        self.schedule = sorted(schedule, key=lambda e: e.at_step)
        self.control_dir = control_dir
        self.ckpt_root = ckpt_root
        self.interval = checkpoint_interval
        self.cloud = cloud
        self.slices = list(slices or [])
        self.accel = accelerator_type
        self.backfill = backfill
        self.poll_s = poll_s
        self.fail_steps: List[int] = []      # last_seen at each restart
        self.log: List[Dict[str, Any]] = []
        self._active_hangs: List[str] = []
        self._backfills = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="chaos-injector",
                                        daemon=True)
        os.makedirs(control_dir, exist_ok=True)

    def start(self) -> "ChaosInjector":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)
        # Release any still-wedged zombie and fence stragglers.
        self._clear_hangs()
        fence(self.control_dir, self.trainer.restarts + 1)

    def injected_counts(self) -> Dict[str, int]:
        out = {k: 0 for k in KINDS}
        for e in self.schedule:
            if e.fired:
                out[e.kind] += 1
        return out

    # ------------------------------------------------------------ loop

    def _run(self) -> None:
        last_restarts = self.trainer.restarts
        while not self._stop.is_set():
            t = self.trainer
            if t.restarts != last_restarts:
                # A gang went down (our doing or the trainer's own
                # supervision): record where, free its hang. Zombie
                # fencing needs no action here — the replacement gang
                # fences its own (newer) attempt id at loop start.
                last_restarts = t.restarts
                self.fail_steps.append(t.last_seen_step or 0)
                self._clear_hangs()
            step = t.last_seen_step
            if step is not None and not t._preempt_pending:
                for ev in self.schedule:
                    if ev.fired or step < ev.at_step:
                        continue
                    if self._fire(ev, step):
                        ev.fired = True
                        ev.fired_at_step = step
                        self.log.append(ev.as_dict())
                    break   # at most one event per tick
            time.sleep(self.poll_s)

    def _clear_hangs(self) -> None:
        for p in self._active_hangs:
            try:
                os.remove(p)
            except OSError:
                pass
        del self._active_hangs[:]

    def _fire(self, ev: ChaosEvent, step: int) -> bool:
        try:
            if ev.kind == "kill":
                return self._fire_kill(ev)
            if ev.kind == "hang":
                return self._fire_hang(ev)
            if ev.kind == "preempt":
                return self._fire_preempt(ev)
            if ev.kind == "torn_ckpt":
                return self._fire_torn(ev)
        except Exception as e:  # noqa: BLE001 - injection must not die
            logger.warning("chaos event %s failed to fire: %s",
                           ev.kind, e)
            return False
        return False

    def _fire_kill(self, ev: ChaosEvent) -> bool:
        group = self.trainer._active_group
        if group is None:
            return False
        rank = ev.rank % group.num_workers
        # Fence FIRST: the killed actor's thread survives the kill in
        # an in-process runtime; advancing the generation to the NEXT
        # attempt id stops it (and its checkpoint commits) within one
        # step. The replacement gang launches with exactly that id, so
        # it is never fenced by its own predecessor's teardown.
        fence(self.control_dir, self.trainer.restarts + 1)
        group.kill_worker(rank)
        return True

    def _fire_hang(self, ev: ChaosEvent) -> bool:
        group = self.trainer._active_group
        if group is None:
            return False
        rank = ev.rank % group.num_workers
        path = _hang_path(self.control_dir, rank)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"hang-{uuid.uuid4().hex}")
        os.replace(tmp, path)
        self._active_hangs.append(path)
        return True

    def _fire_preempt(self, ev: ChaosEvent) -> bool:
        if self.cloud is None or self.trainer._active_group is None:
            return False
        victim = None
        for name in self.slices:
            q = self.cloud.describe(name)
            if q is not None and q["state"] == "READY":
                victim = name
                break
        if victim is None:
            return False
        self.cloud.preempt(victim, grace_s=ev.grace_s,
                           stockout_s=ev.stockout_s)
        self.trainer.notify_preemption(grace_s=ev.grace_s)
        if self.backfill:
            # The cloud backfills capacity eventually; the new slice
            # sits in PROVISIONING until the stockout window passes,
            # which is what lets the gang regrow later.
            self._backfills += 1
            name = f"chaos-backfill-{self._backfills}"
            self.cloud.create_queued_resource(name, self.accel)
            self.slices.append(name)
        return True

    def _fire_torn(self, ev: ChaosEvent) -> bool:
        """Plant a torn checkpoint NEWER than the last durable commit
        — a manifest whose hash no longer matches its payload, i.e. a
        directory a non-atomic writer would have left — then crash the
        gang. Resume must deep-verify, skip it, and land on the last
        real commit."""
        from ray_tpu.air.checkpoint import (MANIFEST_FILE,
                                            MANIFEST_FORMAT)
        from ray_tpu.air.checkpoint_manager import (CheckpointManager,
                                                    step_dir_name)
        mgr = CheckpointManager(self.ckpt_root)
        try:
            last = mgr.latest_step()
        finally:
            mgr.close()
        if last is None:
            return False
        torn_step = last + self.interval
        torn = os.path.join(self.ckpt_root, step_dir_name(torn_step))
        os.makedirs(torn, exist_ok=True)
        with open(os.path.join(torn, "meta.pkl"), "wb") as f:
            f.write(b"\x00torn payload\x00")
        manifest = {"format": MANIFEST_FORMAT, "step": torn_step,
                    "wall_time": 0.0,
                    "files": {"meta.pkl": {
                        "sha256": "0" * 64,
                        "bytes": 14}}}
        with open(os.path.join(torn, MANIFEST_FILE), "w") as f:
            json.dump(manifest, f)
        group = self.trainer._active_group
        if group is not None:
            fence(self.control_dir, self.trainer.restarts + 1)
            group.kill_worker(0)
        return True
