"""Trainers.

Capability parity with the reference's BaseTrainer/DataParallelTrainer
(python/ray/train/base_trainer.py:328, data_parallel_trainer.py:52): a
train_loop_per_worker runs on a WorkerGroup gang, reports stream back, gang
failures trigger elastic restart from the latest checkpoint
(backend_executor.py:512 semantics — for SPMD gangs this is THE fault
tolerance model, per SURVEY.md §7: one member down ⇒ whole-gang
restart-from-checkpoint, not per-task lineage).

JaxTrainer is the TPU-native flagship: the gang spans an ICI slice, each
worker is one host, the loop is an SPMD pjit program over the gang's
MeshSpec. No process-group setup — the mesh IS the collective topology.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import (Checkpoint, InvalidCheckpointError,
                                    load_manifest)
from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.train.worker_group import WorkerGroup
import ray_tpu

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    pass


class GangPreempted(RuntimeError):
    """Internal control flow: the gang drained (or was forced out)
    after a preemption notice. Never consumes the failure budget —
    capacity loss is the platform's doing, not the application's."""

    def __init__(self, msg: str,
                 latest_checkpoint: Optional[Checkpoint] = None):
        super().__init__(msg)
        self.latest_checkpoint = latest_checkpoint


class GangResized(RuntimeError):
    """Internal control flow: a gang running below its requested size
    restarts voluntarily because capacity returned (elastic regrow)."""

    def __init__(self, msg: str,
                 latest_checkpoint: Optional[Checkpoint] = None):
        super().__init__(msg)
        self.latest_checkpoint = latest_checkpoint


def _ckpt_step(ckpt: Optional[Checkpoint]) -> Optional[int]:
    """Cheap step extraction: dict payload key or directory manifest —
    never deserializes array payloads."""
    if ckpt is None:
        return None
    step = None
    if ckpt._data is not None:
        step = ckpt._data.get("step")
    elif ckpt._path is not None:
        try:
            step = load_manifest(ckpt._path).get("step")
        except InvalidCheckpointError:
            step = None
    return step if isinstance(step, int) and not isinstance(step, bool) \
        else None


def _rollback_history(history: list, ckpt: Optional[Checkpoint]) -> None:
    """Exactly-once step semantics for metrics_history across elastic
    restarts: the un-checkpointed tail of the failed attempt never
    durably happened, so drop reported steps beyond the resume
    checkpoint's step — the restarted gang will recompute and re-report
    them. Without this, every restart replays up to a checkpoint
    interval of duplicate steps into the history.

    No checkpoint at all means NOTHING durably happened: the restarted
    gang starts from scratch and will re-report every step, so the
    whole history must go."""
    step = _ckpt_step(ckpt)
    if step is None:
        if ckpt is None:
            del history[:]
        return
    history[:] = [m for m in history
                  if not (isinstance(m, dict)
                          and isinstance(m.get("step"), int)
                          and not isinstance(m.get("step"), bool)
                          and m["step"] > step)]


class BaseTrainer:
    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 elastic_capacity_fn: Optional[Callable[[], int]] = None,
                 elastic_wait_s: float = 30.0):
        self._loop = train_loop_per_worker
        self._config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._resume = resume_from_checkpoint
        # name -> ray_tpu.data.Dataset, sharded per worker at fit()
        # (reference: DataParallelTrainer datasets kwarg +
        # session.get_dataset_shard)
        self._datasets = datasets or {}
        # Elastic capacity oracle: () -> currently-available worker
        # count (e.g. READY slices on a SimulatedTPUCloud). When set,
        # a restart may proceed at reduced size down to
        # ScalingConfig.min_workers while capacity is out, and the
        # gang voluntarily regrows when capacity returns.
        self._capacity_fn = elastic_capacity_fn
        self._elastic_wait_s = elastic_wait_s
        # Live-run state for external supervision (chaos harness /
        # preemption watcher): the active gang + preemption notice.
        self._active_group: Optional[WorkerGroup] = None
        self._preempt_pending = False
        self._preempt_deadline: Optional[float] = None
        # Observability for tests/harnesses.
        self.restarts = 0
        self.preemptions = 0
        self.resizes = 0
        self.world_sizes: list = []
        self.last_seen_step: Optional[int] = None

    def notify_preemption(self, grace_s: float = 5.0) -> bool:
        """Deliver a preemption notice to the running gang: every
        member's ``session.preempted()`` turns True so loops can
        checkpoint-now and drain; if the gang has not drained when the
        grace window closes, it is torn down anyway (the slice is
        gone either way). Returns False when no gang is running."""
        group = self._active_group
        if group is None:
            return False
        self._preempt_deadline = time.time() + grace_s
        self._preempt_pending = True
        group.notify_preemption()
        return True

    # Subclasses decide the mesh the gang builds (None = no device mesh).
    def _mesh_axes(self) -> Optional[Dict[str, int]]:
        return None

    # Subclasses may return a callable(rank, world_size, config) run on
    # each gang member before the loop (framework backend setup).
    def _backend_setup(self) -> Optional[Callable]:
        return None

    def _use_jax_distributed(self, group: WorkerGroup) -> bool:
        """Whether to bootstrap jax.distributed across this gang (see
        ScalingConfig.jax_distributed). Only meaningful for trainers
        that build a device mesh."""
        want = self.scaling_config.jax_distributed
        if want is False or self._mesh_axes() is None or \
                self.scaling_config.num_workers <= 1:
            return False
        can = group.can_bootstrap_gang()
        if want is True and not can:
            raise RuntimeError(
                "ScalingConfig.jax_distributed=True but the gang "
                "members do not occupy distinct OS processes (the "
                "in-process local runtime cannot host a jax.distributed "
                "gang — start a multiprocess Cluster).")
        return can

    def _await_capacity(self) -> int:
        """Gang size for the next attempt. Without a capacity oracle:
        always the requested size. With one: wait (bounded) for at
        least the elastic floor, then take min(requested, available)
        — the data-parallel reshard size."""
        sc = self.scaling_config
        if self._capacity_fn is None:
            return sc.num_workers
        floor = sc.num_workers if sc.min_workers is None \
            else max(1, min(sc.min_workers, sc.num_workers))
        deadline = time.time() + self._elastic_wait_s
        while True:
            avail = int(self._capacity_fn())
            if avail >= floor:
                return max(floor, min(sc.num_workers, avail))
            if time.time() >= deadline:
                raise TrainingFailedError(
                    f"elastic capacity wait timed out: {avail} "
                    f"worker(s) available < floor {floor} after "
                    f"{self._elastic_wait_s}s")
            time.sleep(0.05)

    def fit(self) -> Result:
        from ray_tpu._private.usage_stats import record_library_usage
        record_library_usage("train")
        failure_config = (self.run_config.failure_config or
                          FailureConfig())
        max_failures = failure_config.max_failures
        attempt = 0
        last_fail_step: Optional[int] = None
        latest_ckpt = self._resume
        history: list = []
        while True:
            try:
                num_workers = self._await_capacity()
                return self._run_once(latest_ckpt, history, num_workers)
            except GangPreempted as e:
                self.preemptions += 1
                self.restarts += 1
                latest_ckpt = e.latest_checkpoint or latest_ckpt
                _rollback_history(history, latest_ckpt)
                mp = failure_config.max_preemptions
                if mp != -1 and self.preemptions > mp:
                    logger.error("Preemption budget exhausted (%d)", mp)
                    return Result(
                        metrics=history[-1] if history else None,
                        checkpoint=latest_ckpt,
                        error=e, metrics_history=history)
                logger.warning(
                    "Gang preempted (%s); elastic resume %d from %s",
                    e, self.preemptions, latest_ckpt)
            except GangResized as e:
                self.resizes += 1
                self.restarts += 1
                latest_ckpt = e.latest_checkpoint or latest_ckpt
                _rollback_history(history, latest_ckpt)
                logger.info("Capacity returned; regrowing gang from %s",
                            latest_ckpt)
            except TrainingFailedError as e:
                cause = e.__cause__ or e
                new_ckpt = getattr(e, "latest_checkpoint",
                                   None) or latest_ckpt
                new_step = _ckpt_step(new_ckpt)
                # Durable forward progress since the previous failure
                # resets the retry budget: max_failures bounds
                # CONSECUTIVE unproductive restarts, so intermittent
                # faults on a long run can't exhaust it while the run
                # is actually advancing.
                if new_step is not None and last_fail_step is not None \
                        and new_step > last_fail_step:
                    attempt = 0
                if new_step is not None:
                    last_fail_step = new_step
                if max_failures != -1 and attempt >= max_failures:
                    logger.error("Training failed permanently: %s", cause)
                    return Result(
                        metrics=history[-1] if history else None,
                        checkpoint=latest_ckpt,
                        error=cause, metrics_history=history)
                attempt += 1
                self.restarts += 1
                latest_ckpt = new_ckpt
                _rollback_history(history, latest_ckpt)
                logger.warning(
                    "Gang failure (%s); elastic restart %d/%s from %s",
                    cause, attempt,
                    "inf" if max_failures == -1 else max_failures,
                    latest_ckpt)

    def _run_once(self, resume_ckpt: Optional[Checkpoint],
                  history: list,
                  num_workers: Optional[int] = None) -> Result:
        sc = self.scaling_config
        if num_workers is None:
            num_workers = sc.num_workers
        failure_config = (self.run_config.failure_config or
                          FailureConfig())
        progress_deadline = failure_config.worker_progress_deadline_s
        # Gang trainers get dedicated FRESH worker processes so
        # jax.distributed bootstrap (and re-bootstrap after an elastic
        # restart) is reliable — a process joins one coordinator ever.
        want_gang = (sc.jax_distributed is not False and
                     num_workers > 1 and
                     self._mesh_axes() is not None)
        group = WorkerGroup(num_workers, sc.worker_resources(),
                            sc.placement_strategy,
                            dedicated_processes=want_gang)
        latest_ckpt = resume_ckpt
        last_metrics: Optional[Dict[str, Any]] = None
        # RunConfig(stop=...) applies to plain trainer fits too (the
        # reference runs trainers as tune trials, so stop conditions
        # reach them either way); rank 0's report stream drives it.
        from ray_tpu.tune.stopper import coerce_stopper
        stopper = coerce_stopper(getattr(self.run_config, "stop",
                                         None))
        stop_requested = False
        datasets_per_rank = None
        if self._datasets:
            # Equal-row shards per worker (slice task graph — rows
            # never visit the driver); each rank sees only its shard
            # via session.get_dataset_shard(name). Resharded to the
            # CURRENT gang size on every elastic restart.
            per_name = {name: ds.split(num_workers)
                        for name, ds in self._datasets.items()}
            datasets_per_rank = [
                {name: shards[rank]
                 for name, shards in per_name.items()}
                for rank in range(num_workers)]
        self._preempt_pending = False
        self._preempt_deadline = None
        self._active_group = group
        self.world_sizes.append(num_workers)
        last_regrow_check = time.time()
        try:
            # The attempt id doubles as a fencing token: restarts is
            # monotonic, so a loop from a torn-down gang can detect it
            # has been superseded (session.get_attempt()).
            run_refs = group.start_run(self._loop, self._config,
                                       self._mesh_axes(), resume_ckpt,
                                       self._backend_setup(),
                                       self._use_jax_distributed(group),
                                       datasets_per_rank,
                                       attempt=self.restarts)
            done = [False] * num_workers
            error: Optional[BaseException] = None
            while not all(done) and error is None and \
                    not stop_requested:
                polls = group.poll_all()
                for rank, p in enumerate(polls):
                    for metrics, ckpt in p["reports"]:
                        if rank == 0:
                            last_metrics = metrics
                            history.append(metrics)
                            step = metrics.get("step") if \
                                isinstance(metrics, dict) else None
                            if isinstance(step, int) and \
                                    not isinstance(step, bool):
                                self.last_seen_step = step
                            if stopper is not None and (
                                    stopper("train", metrics) or
                                    stopper.stop_all()):
                                # Reports arrive in bursts; drop the
                                # rest of the batch or a fast loop
                                # blows straight past the condition.
                                stop_requested = True
                                break
                        if ckpt is not None and rank == 0:
                            latest_ckpt = ckpt
                    done[rank] = p["done"]
                    if p["error"] is not None:
                        error = p["error"]
                    if stop_requested:
                        break
                now = time.time()
                if error is None and progress_deadline:
                    # Heartbeat supervision: a member that is alive
                    # (answers polls) but reports no progress past the
                    # deadline is wedged — restart the gang instead of
                    # polling forever. Dead members already surfaced
                    # through their poll entry's error.
                    for rank, p in enumerate(polls):
                        lp = p.get("last_progress")
                        if (not p["done"] and not p.get("dead")
                                and lp is not None
                                and now - lp > progress_deadline):
                            error = TimeoutError(
                                f"worker {rank} made no progress for "
                                f"{now - lp:.2f}s (deadline "
                                f"{progress_deadline}s): wedged")
                            break
                if self._preempt_pending and error is None and \
                        not stop_requested and not all(done) and \
                        now > (self._preempt_deadline or now):
                    # Grace window closed without a full drain: the
                    # slice is going away regardless — take whatever
                    # checkpoint the gang managed to flush.
                    raise GangPreempted(
                        "grace window expired before the gang "
                        "drained", latest_checkpoint=latest_ckpt)
                if error is None and self._capacity_fn is not None \
                        and num_workers < sc.num_workers \
                        and not self._preempt_pending \
                        and not stop_requested \
                        and now - last_regrow_check > 0.25:
                    last_regrow_check = now
                    if int(self._capacity_fn()) >= sc.num_workers:
                        raise GangResized(
                            f"capacity returned ({sc.num_workers} "
                            f"available, running {num_workers})",
                            latest_checkpoint=latest_ckpt)
                if error is None and not all(done) and \
                        not stop_requested:
                    time.sleep(0.01)
            if self._preempt_pending and error is None and \
                    not stop_requested:
                # Clean drain: every member saw the notice, flushed a
                # checkpoint, and returned inside the grace window.
                raise GangPreempted("gang drained after preemption "
                                    "notice",
                                    latest_checkpoint=latest_ckpt)
            if stop_requested and error is None:
                # Condition met: the gang is torn down in finally; the
                # result carries everything reported so far.
                return Result(metrics=last_metrics,
                              checkpoint=latest_ckpt,
                              metrics_history=list(history))
            if error is None:
                # Surface any run() failure not seen via poll.
                try:
                    ray_tpu.get(run_refs, timeout=60)
                except Exception as e:  # noqa: BLE001
                    error = e
            if error is not None:
                exc = TrainingFailedError(str(error))
                exc.latest_checkpoint = latest_ckpt
                raise exc from error
            return Result(metrics=last_metrics, checkpoint=latest_ckpt,
                          metrics_history=list(history))
        finally:
            self._active_group = None
            group.shutdown()


class DataParallelTrainer(BaseTrainer):
    """CPU/host data-parallel trainer (generic loops, no device mesh)."""


class JaxTrainer(BaseTrainer):
    """SPMD trainer over a TPU mesh.

    Single-host: one gang worker builds the mesh over all local chips.
    Multi-host: one worker per host; the distributed runtime launches
    jax.distributed so the mesh spans the slice (same loop code).
    """

    def _mesh_axes(self) -> Optional[Dict[str, int]]:
        spec = self.scaling_config.mesh_spec()
        if spec is None:
            return {"data": -1}    # pure DP over all visible devices
        return spec.sizes()
