"""Trainers.

Capability parity with the reference's BaseTrainer/DataParallelTrainer
(python/ray/train/base_trainer.py:328, data_parallel_trainer.py:52): a
train_loop_per_worker runs on a WorkerGroup gang, reports stream back, gang
failures trigger elastic restart from the latest checkpoint
(backend_executor.py:512 semantics — for SPMD gangs this is THE fault
tolerance model, per SURVEY.md §7: one member down ⇒ whole-gang
restart-from-checkpoint, not per-task lineage).

JaxTrainer is the TPU-native flagship: the gang spans an ICI slice, each
worker is one host, the loop is an SPMD pjit program over the gang's
MeshSpec. No process-group setup — the mesh IS the collective topology.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.train.worker_group import WorkerGroup
import ray_tpu

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    pass


class BaseTrainer:
    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self._loop = train_loop_per_worker
        self._config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._resume = resume_from_checkpoint
        # name -> ray_tpu.data.Dataset, sharded per worker at fit()
        # (reference: DataParallelTrainer datasets kwarg +
        # session.get_dataset_shard)
        self._datasets = datasets or {}

    # Subclasses decide the mesh the gang builds (None = no device mesh).
    def _mesh_axes(self) -> Optional[Dict[str, int]]:
        return None

    # Subclasses may return a callable(rank, world_size, config) run on
    # each gang member before the loop (framework backend setup).
    def _backend_setup(self) -> Optional[Callable]:
        return None

    def _use_jax_distributed(self, group: WorkerGroup) -> bool:
        """Whether to bootstrap jax.distributed across this gang (see
        ScalingConfig.jax_distributed). Only meaningful for trainers
        that build a device mesh."""
        want = self.scaling_config.jax_distributed
        if want is False or self._mesh_axes() is None or \
                self.scaling_config.num_workers <= 1:
            return False
        can = group.can_bootstrap_gang()
        if want is True and not can:
            raise RuntimeError(
                "ScalingConfig.jax_distributed=True but the gang "
                "members do not occupy distinct OS processes (the "
                "in-process local runtime cannot host a jax.distributed "
                "gang — start a multiprocess Cluster).")
        return can

    def fit(self) -> Result:
        from ray_tpu._private.usage_stats import record_library_usage
        record_library_usage("train")
        failure_config = (self.run_config.failure_config or
                          FailureConfig())
        max_failures = failure_config.max_failures
        attempt = 0
        latest_ckpt = self._resume
        history: list = []
        while True:
            try:
                return self._run_once(latest_ckpt, history)
            except TrainingFailedError as e:
                cause = e.__cause__ or e
                if max_failures != -1 and attempt >= max_failures:
                    logger.error("Training failed permanently: %s", cause)
                    return Result(
                        metrics=history[-1] if history else None,
                        checkpoint=latest_ckpt,
                        error=cause, metrics_history=history)
                attempt += 1
                latest_ckpt = getattr(e, "latest_checkpoint",
                                      None) or latest_ckpt
                logger.warning(
                    "Gang failure (%s); elastic restart %d/%s from %s",
                    cause, attempt,
                    "inf" if max_failures == -1 else max_failures,
                    latest_ckpt)

    def _run_once(self, resume_ckpt: Optional[Checkpoint],
                  history: list) -> Result:
        sc = self.scaling_config
        # Gang trainers get dedicated FRESH worker processes so
        # jax.distributed bootstrap (and re-bootstrap after an elastic
        # restart) is reliable — a process joins one coordinator ever.
        want_gang = (sc.jax_distributed is not False and
                     sc.num_workers > 1 and
                     self._mesh_axes() is not None)
        group = WorkerGroup(sc.num_workers, sc.worker_resources(),
                            sc.placement_strategy,
                            dedicated_processes=want_gang)
        latest_ckpt = resume_ckpt
        last_metrics: Optional[Dict[str, Any]] = None
        # RunConfig(stop=...) applies to plain trainer fits too (the
        # reference runs trainers as tune trials, so stop conditions
        # reach them either way); rank 0's report stream drives it.
        from ray_tpu.tune.stopper import coerce_stopper
        stopper = coerce_stopper(getattr(self.run_config, "stop",
                                         None))
        stop_requested = False
        datasets_per_rank = None
        if self._datasets:
            # Equal-row shards per worker (slice task graph — rows
            # never visit the driver); each rank sees only its shard
            # via session.get_dataset_shard(name).
            per_name = {name: ds.split(sc.num_workers)
                        for name, ds in self._datasets.items()}
            datasets_per_rank = [
                {name: shards[rank]
                 for name, shards in per_name.items()}
                for rank in range(sc.num_workers)]
        try:
            run_refs = group.start_run(self._loop, self._config,
                                       self._mesh_axes(), resume_ckpt,
                                       self._backend_setup(),
                                       self._use_jax_distributed(group),
                                       datasets_per_rank)
            done = [False] * sc.num_workers
            error: Optional[BaseException] = None
            while not all(done) and error is None and \
                    not stop_requested:
                polls = group.poll_all()
                for rank, p in enumerate(polls):
                    for metrics, ckpt in p["reports"]:
                        if rank == 0:
                            last_metrics = metrics
                            history.append(metrics)
                            if stopper is not None and (
                                    stopper("train", metrics) or
                                    stopper.stop_all()):
                                # Reports arrive in bursts; drop the
                                # rest of the batch or a fast loop
                                # blows straight past the condition.
                                stop_requested = True
                                break
                        if ckpt is not None and rank == 0:
                            latest_ckpt = ckpt
                    done[rank] = p["done"]
                    if p["error"] is not None:
                        error = p["error"]
                    if stop_requested:
                        break
                if error is None and not all(done) and \
                        not stop_requested:
                    time.sleep(0.01)
            if stop_requested and error is None:
                # Condition met: the gang is torn down in finally; the
                # result carries everything reported so far.
                return Result(metrics=last_metrics,
                              checkpoint=latest_ckpt,
                              metrics_history=list(history))
            if error is None:
                # Surface any run() failure not seen via poll.
                try:
                    ray_tpu.get(run_refs, timeout=60)
                except Exception as e:  # noqa: BLE001
                    error = e
            if error is not None:
                exc = TrainingFailedError(str(error))
                exc.latest_checkpoint = latest_ckpt
                raise exc from error
            return Result(metrics=last_metrics, checkpoint=latest_ckpt,
                          metrics_history=list(history))
        finally:
            group.shutdown()


class DataParallelTrainer(BaseTrainer):
    """CPU/host data-parallel trainer (generic loops, no device mesh)."""


class JaxTrainer(BaseTrainer):
    """SPMD trainer over a TPU mesh.

    Single-host: one gang worker builds the mesh over all local chips.
    Multi-host: one worker per host; the distributed runtime launches
    jax.distributed so the mesh spans the slice (same loop code).
    """

    def _mesh_axes(self) -> Optional[Dict[str, int]]:
        spec = self.scaling_config.mesh_spec()
        if spec is None:
            return {"data": -1}    # pure DP over all visible devices
        return spec.sizes()
