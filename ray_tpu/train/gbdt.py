"""Tree/estimator trainers: sklearn, xgboost-API, lightgbm-API.

Capability parity with the reference's GBDT + sklearn trainers
(python/ray/train/xgboost/, lightgbm/, sklearn/ — a Trainer that fits
an estimator on a Dataset and emits a framework Checkpoint).
XGBoostTrainer/LightGBMTrainer accept their libraries' params dicts
and run on sklearn's histogram-GBDT engine when the native package is
absent (as in this image), or pass through to the real library when
it is importable.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result


def _assemble_xy(block_refs, label_column: str):
    """Stream dataset blocks into feature/label arrays block-by-block
    — runs INSIDE the fit worker, so rows never co-reside in the
    driver (reference: train/gbdt_trainer.py distributes the data
    loading to the training workers)."""
    import ray_tpu
    feats = None
    Xs, ys = [], []
    for ref in block_refs:
        rows = ray_tpu.get(ref)
        if not rows:
            continue
        if feats is None:
            feats = [k for k in rows[0] if k != label_column]
        ys.append(np.asarray([r[label_column] for r in rows]))
        Xs.append(np.asarray([[r[k] for k in feats] for r in rows],
                             np.float64))
    if not Xs:
        raise ValueError("dataset is empty")
    return np.concatenate(Xs), np.concatenate(ys)


def _fit_task(est, train_refs, valid_refs, label_column: str,
              metric_fn):
    """Worker-side fit: assemble shards, fit, score. Returns the
    fitted estimator + metrics + the fitting pid (provenance: proves
    the driver never touched the rows)."""
    import os
    X, y = _assemble_xy(train_refs, label_column)
    est.fit(X, y)
    metrics = {f"train-{k}": v for k, v in metric_fn(est, X, y).items()}
    if valid_refs is not None:
        Xv, yv = _assemble_xy(valid_refs, label_column)
        metrics.update({f"valid-{k}": v
                        for k, v in metric_fn(est, Xv, yv).items()})
    metrics["fit_pid"] = os.getpid()
    return est, metrics


def _run_remote_fit(est, datasets, label_column, metric_fn,
                    scaling_config):
    """Dispatch the fit as a task (driver holds only block REFS)."""
    import ray_tpu
    train_refs = list(datasets["train"].materialize()._block_refs)
    valid = datasets.get("valid")
    valid_refs = list(valid.materialize()._block_refs) \
        if valid is not None else None
    opts = {}
    res = getattr(scaling_config, "resources_per_worker", None)
    if res:
        cpus = res.get("CPU")
        if cpus:
            opts["num_cpus"] = cpus
        extra = {k: v for k, v in res.items() if k != "CPU"}
        if extra:
            opts["resources"] = extra
    fn = ray_tpu.remote(_fit_task)
    if opts:
        fn = fn.options(**opts)
    return ray_tpu.get(fn.remote(est, train_refs, valid_refs,
                                 label_column, metric_fn))


class SklearnTrainer:
    """Fit any sklearn estimator on a Dataset (reference:
    train/sklearn/sklearn_trainer.py)."""

    def __init__(self, *, estimator, datasets: Dict[str, Any],
                 label_column: str,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> Result:
        from ray_tpu._private.usage_stats import record_library_usage
        record_library_usage("train")
        est, metrics = _run_remote_fit(
            self.estimator, self.datasets, self.label_column,
            lambda e, X, y: {"score": float(e.score(X, y))},
            self.scaling_config)
        metrics = {k.replace("-", "_"): v for k, v in metrics.items()}
        self.estimator = est
        ckpt = Checkpoint.from_dict({"estimator": est})
        return Result(metrics=metrics, checkpoint=ckpt,
                      metrics_history=[metrics])


class _GBDTTrainer:
    """Shared engine for the GBDT trainer API (reference:
    train/xgboost/xgboost_trainer.py, train/lightgbm/lightgbm_trainer.py:
    params dict + num_boost_round + datasets -> fitted booster +
    Checkpoint + per-dataset eval metrics).

    The tree engine is sklearn's histogram-based GBDT (the same
    algorithm family LightGBM introduced and XGBoost's `hist` mode
    uses), so these trainers WORK in this environment; when the real
    xgboost/lightgbm package is importable it is used instead and the
    params pass through natively."""

    #: subclass hooks: params-dict translation + native passthrough
    _param_map: Dict[str, str] = {}
    _native_module = ""
    _native_classes = ("", "")       # (classifier, regressor) names

    def __init__(self, *, params: Optional[Dict[str, Any]] = None,
                 num_boost_round: int = 100,
                 datasets: Dict[str, Any], label_column: str,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.params = dict(params or {})
        self.num_boost_round = num_boost_round
        self.datasets = datasets
        self.label_column = label_column
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()

    # -- objective handling -------------------------------------------

    def _is_classification(self) -> bool:
        # xgboost objectives are "<task>:<loss>" (reg:logistic is
        # REGRESSION); lightgbm uses bare names. Classification iff
        # the task prefix says so.
        obj = str(self.params.get("objective", ""))
        task = obj.split(":", 1)[0]
        return task in ("binary", "multi", "multiclass",
                        "multiclassova")

    def _make_estimator(self):
        from sklearn.ensemble import (HistGradientBoostingClassifier,
                                      HistGradientBoostingRegressor)
        kwargs: Dict[str, Any] = {"max_iter": self.num_boost_round}
        for theirs, ours in self._param_map.items():
            if theirs in self.params:
                kwargs[ours] = self.params[theirs]
        cls = HistGradientBoostingClassifier \
            if self._is_classification() \
            else HistGradientBoostingRegressor
        return cls(**kwargs)

    def _make_native_or_fallback(self):
        import importlib
        try:
            mod = importlib.import_module(self._native_module)
        except ImportError:
            return self._make_estimator()
        name = self._native_classes[0] if self._is_classification() \
            else self._native_classes[1]     # pragma: no cover
        cls = getattr(mod, name)             # pragma: no cover
        return cls(n_estimators=self.num_boost_round, **{
            k: v for k, v in self.params.items()
            if k != "objective"})            # pragma: no cover

    def fit(self) -> Result:
        from ray_tpu._private.usage_stats import record_library_usage
        record_library_usage("train")
        est = self._make_native_or_fallback()
        classification = self._is_classification()

        def metric_fn(e, X, y, _cls=classification):
            if _cls:
                return {"error": float(1.0 - e.score(X, y))}
            pred = e.predict(X)
            return {"rmse": float(np.sqrt(np.mean((pred - y) ** 2)))}

        est, metrics = _run_remote_fit(
            est, self.datasets, self.label_column, metric_fn,
            self.scaling_config)
        ckpt = Checkpoint.from_dict({"estimator": est,
                                     "params": dict(self.params)})
        return Result(metrics=metrics, checkpoint=ckpt,
                      metrics_history=[metrics])

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        """The fitted booster/estimator out of a Checkpoint
        (reference: XGBoostTrainer.get_model)."""
        return checkpoint.to_dict()["estimator"]


class XGBoostTrainer(_GBDTTrainer):
    """xgboost-API trainer (params: objective/eta/max_depth/...);
    runs on sklearn's histogram GBDT when xgboost is absent."""
    _param_map = {"eta": "learning_rate",
                  "learning_rate": "learning_rate",
                  "max_depth": "max_depth",
                  "reg_lambda": "l2_regularization",
                  "lambda": "l2_regularization",
                  "min_child_weight": "min_samples_leaf"}
    _native_module = "xgboost"
    _native_classes = ("XGBClassifier", "XGBRegressor")


class LightGBMTrainer(_GBDTTrainer):
    """lightgbm-API trainer (params: objective/num_leaves/...);
    runs on sklearn's histogram GBDT when lightgbm is absent."""
    _param_map = {"learning_rate": "learning_rate",
                  "num_leaves": "max_leaf_nodes",
                  "max_depth": "max_depth",
                  "lambda_l2": "l2_regularization",
                  "min_data_in_leaf": "min_samples_leaf"}
    _native_module = "lightgbm"
    _native_classes = ("LGBMClassifier", "LGBMRegressor")
