"""Tree/estimator trainers: sklearn first-class, xgboost/lightgbm gated.

Capability parity with the reference's GBDT + sklearn trainers
(python/ray/train/xgboost/, lightgbm/, sklearn/ — a Trainer that fits
an estimator on a Dataset and emits a framework Checkpoint). xgboost and
lightgbm are not in this image, so those trainer classes raise a clear
ImportError at construction; SklearnTrainer carries the shared shape.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result


def _dataset_to_xy(ds, label_column: str):
    rows = ds.take_all()
    y = np.asarray([r[label_column] for r in rows])
    feats = [k for k in rows[0] if k != label_column]
    X = np.asarray([[r[k] for k in feats] for r in rows], np.float64)
    return X, y


class SklearnTrainer:
    """Fit any sklearn estimator on a Dataset (reference:
    train/sklearn/sklearn_trainer.py)."""

    def __init__(self, *, estimator, datasets: Dict[str, Any],
                 label_column: str,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> Result:
        from ray_tpu._private.usage_stats import record_library_usage
        record_library_usage("train")
        X, y = _dataset_to_xy(self.datasets["train"], self.label_column)
        self.estimator.fit(X, y)
        metrics: Dict[str, Any] = {
            "train_score": float(self.estimator.score(X, y))}
        valid = self.datasets.get("valid")
        if valid is not None:
            Xv, yv = _dataset_to_xy(valid, self.label_column)
            metrics["valid_score"] = float(self.estimator.score(Xv, yv))
        ckpt = Checkpoint.from_dict({"estimator": self.estimator})
        return Result(metrics=metrics, checkpoint=ckpt,
                      metrics_history=[metrics])


def _gated(name: str, module: str):
    class _GatedTrainer:
        def __init__(self, *a, **kw):
            raise ImportError(
                f"{name} requires {module!r}, which is not available "
                f"in this environment; use SklearnTrainer (e.g. "
                f"HistGradientBoostingRegressor/Classifier) instead.")
    _GatedTrainer.__name__ = name
    return _GatedTrainer


try:
    import xgboost  # noqa: F401
    _HAS_XGB = True
except ImportError:
    _HAS_XGB = False

if not _HAS_XGB:
    XGBoostTrainer = _gated("XGBoostTrainer", "xgboost")
else:   # pragma: no cover - xgboost not in this image
    class XGBoostTrainer(SklearnTrainer):
        pass

try:
    import lightgbm  # noqa: F401
    _HAS_LGBM = True
except ImportError:
    _HAS_LGBM = False

if not _HAS_LGBM:
    LightGBMTrainer = _gated("LightGBMTrainer", "lightgbm")
else:   # pragma: no cover
    class LightGBMTrainer(SklearnTrainer):
        pass
