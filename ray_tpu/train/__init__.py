from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.train.trainer import BaseTrainer, JaxTrainer, DataParallelTrainer

__all__ = ["BaseTrainer", "JaxTrainer", "DataParallelTrainer",
           "ScalingConfig", "RunConfig", "FailureConfig",
           "CheckpointConfig", "Result"]
