from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.train import gang
from ray_tpu.train.gbdt import (LightGBMTrainer, SklearnTrainer,
                                XGBoostTrainer)
from ray_tpu.train.predictor import (BatchPredictor, JaxPredictor,
                                     Predictor, SklearnPredictor)
from ray_tpu.train.compose import (make_composed_loss,
                                   make_composed_train_step,
                                   put_composed_batch)
from ray_tpu.train.trainer import BaseTrainer, JaxTrainer, DataParallelTrainer
from ray_tpu.train.torch import TorchTrainer
from ray_tpu.train.huggingface import HuggingFaceTrainer

__all__ = ["gang", "BaseTrainer", "JaxTrainer", "DataParallelTrainer",
           "TorchTrainer", "HuggingFaceTrainer",
           "SklearnTrainer", "XGBoostTrainer",
           "LightGBMTrainer", "Predictor", "JaxPredictor",
           "SklearnPredictor", "BatchPredictor",
           "ScalingConfig", "RunConfig", "FailureConfig",
           "CheckpointConfig", "Result", "make_composed_train_step",
           "make_composed_loss", "put_composed_batch"]
