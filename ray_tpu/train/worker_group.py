"""WorkerGroup: the actor gang a trainer runs on.

Capability parity with the reference's WorkerGroup + BackendExecutor
(python/ray/train/_internal/worker_group.py:91,
train/_internal/backend_executor.py:42): N actors created under a PACK
placement group, train loops launched asynchronously, results gathered one
per worker per round, gang teardown/restart on failure. TPU-native: a worker
is a *host* of an SPMD gang; its method runs a pjit-compiled loop over the
host's chips, and a MeshSpec (not a process-group backend) defines the
collective topology.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air import session as air_session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.util import (PlacementGroupSchedulingStrategy, placement_group,
                          remove_placement_group)


class TrainWorker:
    """Actor running one gang member's train loop, buffering reports."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self._buffer: List[tuple] = []
        self._lock = threading.Lock()
        self._done = False
        self._error: Optional[BaseException] = None
        # Gang supervision: last observed progress (reports/heartbeats)
        # and the preemption notice flag the loop polls.
        self._last_progress = time.time()
        self._preempt = False

    def process_identity(self) -> str:
        """Collision-free per-process id (PIDs/hostnames repeat across
        containers; see gang.PROCESS_UUID)."""
        from ray_tpu.train import gang
        return gang.PROCESS_UUID

    def gang_endpoint(self) -> str:
        """Allocate (or reuse) the jax.distributed coordinator endpoint
        on this host — called on the rank-0 member only."""
        from ray_tpu.train import gang
        return gang.coordinator_endpoint()

    def run(self, loop_fn: Callable, config: Dict[str, Any],
            mesh_axes: Optional[Dict[str, int]],
            resume_checkpoint: Optional[Checkpoint],
            backend_setup: Optional[Callable] = None,
            gang_bootstrap: Optional[Dict[str, Any]] = None,
            datasets: Optional[Dict[str, Any]] = None,
            attempt: int = 0) -> str:
        if gang_bootstrap is not None:
            # Join the jax.distributed gang BEFORE any jax computation:
            # after this, jax.devices() spans every member's chips and
            # the mesh below is a true multi-host mesh.
            from ray_tpu.train import gang
            gang.init_gang(gang_bootstrap["coordinator"],
                           gang_bootstrap["num_processes"],
                           self.rank)
        mesh = None
        if mesh_axes is not None:
            from ray_tpu.mesh import create_mesh
            mesh = create_mesh(mesh_axes)
        if backend_setup is not None:
            # Framework backend hook run on each gang member before the
            # loop (reference: Backend.on_start, e.g. torch process
            # group setup in train/torch/config.py:54).
            backend_setup(self.rank, self.world_size, config)

        def report_fn(metrics, checkpoint):
            with self._lock:
                self._buffer.append((metrics, checkpoint))

        def heartbeat_fn():
            with self._lock:
                self._last_progress = time.time()

        def preempt_fn():
            with self._lock:
                return self._preempt

        with self._lock:
            self._last_progress = time.time()
        ctx = air_session.TrainContext(
            world_rank=self.rank, world_size=self.world_size,
            report_fn=report_fn, mesh=mesh,
            checkpoint=resume_checkpoint, config=config,
            datasets=datasets, heartbeat_fn=heartbeat_fn,
            preempt_fn=preempt_fn, attempt=attempt)
        air_session.set_context(ctx)
        try:
            if _takes_arg(loop_fn):
                loop_fn(config)
            else:
                loop_fn()
            return "done"
        except BaseException as e:  # noqa: BLE001
            with self._lock:
                self._error = e
            raise
        finally:
            with self._lock:
                self._done = True
            air_session.set_context(None)

    def poll(self):
        """Drain buffered (metrics, checkpoint) reports + status.
        ``last_progress`` is the wall time of the newest report or
        heartbeat (the trainer's hang detector input); poll() itself
        deliberately does NOT count — a wedged loop keeps answering
        polls, which is exactly why liveness != progress."""
        with self._lock:
            out = list(self._buffer)
            self._buffer.clear()
            return {"reports": out, "done": self._done,
                    "error": self._error, "dead": False,
                    "last_progress": self._last_progress,
                    "preempted": self._preempt}

    def request_preemption(self):
        """Deliver a preemption notice: session.preempted() turns True
        on this worker so the loop can checkpoint-now and drain."""
        with self._lock:
            self._preempt = True
        return True

    def shutdown_marker(self):
        return True


def _takes_arg(fn) -> bool:
    import inspect
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len([p for p in sig.parameters.values()
                if p.default is p.empty and
                p.kind in (p.POSITIONAL_ONLY,
                           p.POSITIONAL_OR_KEYWORD)]) >= 1


class WorkerGroup:
    """A gang of TrainWorker actors under one placement group."""

    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK",
                 dedicated_processes: bool = False):
        self.num_workers = num_workers
        self._dedicated_worker_ids: List[str] = []
        self._head = None
        if dedicated_processes:
            resources_per_worker, placement_strategy = \
                self._spawn_dedicated(num_workers,
                                      dict(resources_per_worker))
        self._pg = placement_group(
            [dict(resources_per_worker) for _ in range(num_workers)],
            strategy=placement_strategy)
        if not self._pg.wait(60):
            remove_placement_group(self._pg)
            raise TimeoutError(
                f"Could not reserve {num_workers}x"
                f"{resources_per_worker} for the worker group")
        actor_cls = ray_tpu.remote(TrainWorker)
        self.workers = []
        for rank in range(num_workers):
            w = actor_cls.options(
                # The PG already reserved the resources.
                num_cpus=0,
                max_concurrency=2,   # run() + poll() concurrently
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self._pg,
                    placement_group_bundle_index=rank),
            ).remote(rank, num_workers)
            self.workers.append(w)

    def _spawn_dedicated(self, num_workers, resources):
        """Spawn one FRESH worker process per gang member, tagged with a
        one-off token resource so the placement group lands exactly on
        them. Fresh processes are what make jax.distributed bootstrap
        (and gang re-bootstrap after an elastic restart) reliable: a
        process can only ever join one coordinator (gang.init_gang).
        Reference shape: dedicated train-worker processes under the
        Train placement group (backend_executor.py:137).

        No-op (returns inputs unchanged) on the in-process local
        runtime, which has no worker processes to spawn."""
        import uuid
        from ray_tpu._private.worker import global_worker
        head = getattr(global_worker().runtime, "head", None)
        if head is None:
            return resources, "PACK"
        token = f"_gang_{uuid.uuid4().hex[:8]}"
        res = dict(resources)
        res[token] = 1.0
        for _ in range(num_workers):
            self._dedicated_worker_ids.append(
                head.call("request_worker", res))
        deadline = time.time() + 60
        while time.time() < deadline:
            alive = [w for w in head.call("list_workers")
                     if w["alive"] and token in w.get("resources", {})]
            if len(alive) >= num_workers:
                break
            time.sleep(0.05)
        else:
            raise TimeoutError(
                f"dedicated gang workers did not register: "
                f"{self._dedicated_worker_ids}")
        self._head = head
        return res, "STRICT_SPREAD"

    def can_bootstrap_gang(self) -> bool:
        """jax.distributed needs one OS process per member: true iff all
        members landed in distinct processes, none of them the driver
        (the local thread-runtime runs actors in-process)."""
        from ray_tpu.train import gang
        ids = ray_tpu.get(
            [w.process_identity.remote() for w in self.workers])
        return (len(set(ids)) == self.num_workers and
                gang.PROCESS_UUID not in ids)

    def start_run(self, loop_fn, config, mesh_axes, resume_checkpoint,
                  backend_setup=None, jax_distributed=False,
                  datasets_per_rank=None, attempt=0):
        gang_bootstrap = None
        if jax_distributed:
            coordinator = ray_tpu.get(
                self.workers[0].gang_endpoint.remote())
            gang_bootstrap = {"coordinator": coordinator,
                              "num_processes": self.num_workers}
        return [w.run.remote(loop_fn, config, mesh_axes,
                             resume_checkpoint, backend_setup,
                             gang_bootstrap,
                             datasets_per_rank[rank]
                             if datasets_per_rank else None,
                             attempt)
                for rank, w in enumerate(self.workers)]

    def poll_all(self) -> List[Dict[str, Any]]:
        """Poll every gang member with per-worker error isolation: a
        dead actor yields a ``dead: True`` entry instead of blowing up
        the whole poll, so survivors' buffered reports (metrics AND
        checkpoints) still reach the trainer on the round a member
        dies — the difference between resuming from the last committed
        step and replaying a whole checkpoint interval."""
        refs: List[Any] = []
        for w in self.workers:
            try:
                refs.append(w.poll.remote())
            except Exception as e:  # noqa: BLE001 - submit-time death
                refs.append(e)
        out: List[Dict[str, Any]] = []
        for ref in refs:
            if isinstance(ref, Exception):
                err: Optional[BaseException] = ref
            else:
                try:
                    out.append(ray_tpu.get(ref))
                    continue
                except Exception as e:  # noqa: BLE001
                    err = e
            out.append({"reports": [], "done": False, "error": err,
                        "dead": True, "last_progress": None,
                        "preempted": False})
        return out

    def notify_preemption(self) -> int:
        """Fan the preemption notice out to every reachable member.
        Returns how many acknowledged (dead members are skipped — they
        are already beyond saving)."""
        acked = 0
        for w in self.workers:
            try:
                ray_tpu.get(w.request_preemption.remote())
                acked += 1
            except Exception:  # noqa: BLE001
                pass
        return acked

    def kill_worker(self, rank: int) -> None:
        """Hard-kill one gang member's actor (chaos harness seam — the
        moral equivalent of a host crash, distinct from an exception
        the loop raises itself)."""
        ray_tpu.kill(self.workers[rank])

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        remove_placement_group(self._pg)
        for wid in self._dedicated_worker_ids:
            try:
                self._head.call("stop_worker", wid)
            except Exception:
                pass
