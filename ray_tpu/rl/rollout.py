"""Rollout generation: prompt -> completion batches off the serving
engine, stamped with the weight payload that produced them.

The generator is deliberately dumb about training: it submits on
``LANE_BATCH`` (online traffic admits first, preempts rollouts first,
and rollout TTFT never pollutes the online SLO stats — PR 18's lane
discipline), collects per-token sampling logprobs (the learners'
behavior policy), and stamps every batch with the engine's
``weights_id``/``generation`` at submit time so the loop can detect —
and bound — staleness.

Weight sync happens ONLY at round boundaries (``sync_weights``): a
preempt-mode swap mid-round would recompute in-flight completions
under the new payload and silently mix policies inside the captured
logprobs. The loop enforces the boundary; the generator just exposes
the sync.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional, Sequence


class GeneratorKilled(RuntimeError):
    """Raised by a chaos mid-round hook: the generator died after
    submitting a round but before handing the batch to the learner."""


@dataclasses.dataclass
class RolloutBatch:
    """One round of rollouts, self-describing enough for exactly-once
    accounting: ``batch_id`` is the ledger key, ``weights_id`` /
    ``generation`` name the payload that sampled it, ``logprobs`` are
    the behavior-policy log-probs index-aligned with ``completions``.
    ``rewards`` is stamped later by the loop's scoring stage."""
    batch_id: str
    round_idx: int
    prompts: List[List[int]]
    completions: List[List[int]]
    logprobs: List[List[float]]
    weights_id: str
    generation: int
    rewards: Optional[List[float]] = None
    gen_wall_s: float = 0.0

    def num_samples(self) -> int:
        return len(self.prompts)

    def num_tokens(self) -> int:
        return sum(len(c) for c in self.completions)


class RolloutGenerator:
    """Batched rollout generation over an ``LLMEngine`` or
    ``EnginePool`` (anything with ``submit_rollout_batch``)."""

    def __init__(self, engine, *, max_new_tokens: int = 16):
        self.engine = engine
        self.max_new_tokens = int(max_new_tokens)
        self.rounds_generated = 0

    # ------------------------------------------------------------ stamps

    def weights_stamp(self) -> tuple:
        """(generation, weights_id) currently serving. For a pool this
        is replica 0's stamp — the loop swaps the whole fleet through
        ``sync_weights`` so replicas agree between rounds."""
        eng = self.engine
        if hasattr(eng, "engines"):
            eng = eng.engines()[0]
        return (int(getattr(eng, "weight_generation", 0)),
                str(getattr(eng, "weights_id", "g0")))

    # ------------------------------------------------------------- sync

    def sync_weights(self, params, *, weights_id: str,
                     mode: str = "preempt") -> int:
        """Round-boundary weight sync under the monotonic fence: the
        target generation is always current+1 (the fence never cares
        which update count a payload came from, only that it advances).
        Call with no rollouts in flight. Returns the new generation."""
        eng = self.engine
        if hasattr(eng, "swap_replica_weights"):
            gen = self.weights_stamp()[0] + 1
            eng.set_weight_source(params, weights_id=weights_id,
                                  generation=gen)
            for i in range(len(eng.engines())):
                eng.swap_replica_weights(i, params,
                                         weights_id=weights_id,
                                         generation=gen, mode=mode)
            return gen
        return eng.swap_weights(
            params, generation=eng.weight_generation + 1,
            weights_id=weights_id, mode=mode)

    # --------------------------------------------------------- generate

    def generate(self, prompts: Sequence[Sequence[int]], *,
                 round_idx: int, batch_id: Optional[str] = None,
                 mid_round_hook: Optional[Callable[[int], Any]] = None
                 ) -> RolloutBatch:
        """Generate one round on ``LANE_BATCH``. The weights stamp is
        read at submit time; the loop guarantees no sync intervenes
        mid-round. ``mid_round_hook`` is the chaos seam — it runs after
        submission, before collection, and may raise to simulate the
        generator dying mid-round (in-flight requests are cancelled so
        the engine does not keep decoding for a dead consumer)."""
        gen, wid = self.weights_stamp()
        bid = batch_id if batch_id is not None else f"round-{round_idx}"
        t0 = time.monotonic()
        handles = self.engine.submit_rollout_batch(
            prompts, max_new_tokens=self.max_new_tokens, trace_id=bid)
        try:
            if mid_round_hook is not None:
                mid_round_hook(round_idx)
            completions = [h.result() for h in handles]
        except BaseException:
            for h in handles:
                try:
                    h.cancel()
                except Exception:
                    pass
            raise
        logprobs = [list(h.logprobs or []) for h in handles]
        self.rounds_generated += 1
        return RolloutBatch(
            batch_id=bid, round_idx=round_idx,
            prompts=[list(p) for p in prompts],
            completions=completions, logprobs=logprobs,
            weights_id=wid, generation=gen,
            gen_wall_s=time.monotonic() - t0)
