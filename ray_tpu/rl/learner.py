"""Token-level policy-gradient learner over engine rollout batches.

Reuses the rllib loss pieces on sequence data: ``algo="ppo"`` applies
``rllib.ppo.clipped_surrogate_loss`` with a per-sequence advantage
(reward minus a running scalar baseline, normalized across the batch)
broadcast to every completion token; ``algo="vtrace"`` applies
``rllib.impala.vtrace_returns`` per sequence (reward at the terminal
token, baseline as the constant value estimate) — the off-policy
correction that matters once rollouts lag the learner by a bounded
number of updates (rl/loop.py's staleness knob).

The forward is the model's dense (no-kv-cache) teacher-forced pass:
logits at position ``plen-1+i`` score completion token ``i``. Behavior
logprobs come from the engine's capture (rollout.RolloutBatch), so the
importance ratio is exact even when the batch was sampled a few
generations ago. Shapes are frozen from the first batch (one jit
compile); padding rides adv=0 / mask=0 so it contributes exactly zero
loss.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl.rollout import RolloutBatch
from ray_tpu.rllib.impala import vtrace_returns
from ray_tpu.rllib.ppo import clipped_surrogate_loss


class RolloutLearner:
    def __init__(self, model, params, *, algo: str = "ppo",
                 lr: float = 1e-2, clip_eps: float = 0.2,
                 gamma: float = 1.0, entropy_coef: float = 0.0,
                 baseline_beta: float = 0.2, sgd_epochs: int = 1):
        import optax

        if algo not in ("ppo", "vtrace"):
            raise ValueError(f"unknown algo {algo!r}; expected 'ppo' "
                             f"or 'vtrace'")
        self.model = model
        self.params = params
        self.algo = algo
        self.clip_eps = float(clip_eps)
        self.gamma = float(gamma)
        self.entropy_coef = float(entropy_coef)
        self.baseline_beta = float(baseline_beta)
        self.sgd_epochs = max(1, int(sgd_epochs))
        self.baseline = 0.0
        self.update_count = 0
        self._opt = optax.adam(lr)
        self.opt_state = self._opt.init(params)
        self._shape = None          # (B, L, T) frozen on first update
        self._update_fn = self._build_update()

    # ------------------------------------------------------------- jit

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        model = self.model
        algo = self.algo
        clip_eps = self.clip_eps
        gamma = self.gamma
        entropy_coef = self.entropy_coef

        def loss_fn(params, b):
            logits, _ = model.apply(params, b["tokens"])
            sel = jnp.take_along_axis(
                logits.astype(jnp.float32),
                b["gpos"][:, :, None], axis=1)          # [B, T, V]
            logp_all = jax.nn.log_softmax(sel)
            logp = jnp.take_along_axis(
                logp_all, b["targets"][:, :, None], axis=-1)[..., 0]
            mask = b["mask"]
            denom = jnp.maximum(mask.sum(), 1.0)
            if algo == "ppo":
                adv_tok = b["adv"][:, None] * mask       # pad -> 0 loss
                pg = clipped_surrogate_loss(
                    logp.ravel(), b["behavior"].ravel(),
                    adv_tok.ravel(), clip_eps)
            else:
                rho = jnp.exp(logp - b["behavior"]) * mask
                values = jnp.full_like(logp, b["baseline"])

                def one(v, r, d, rh):
                    return vtrace_returns(v, jnp.float32(0.0), r, d, rh,
                                          gamma=gamma)

                _vs, pg_adv = jax.vmap(one)(
                    values, b["rew_tok"], b["dones"], rho)
                pg = -(mask * logp * pg_adv).sum() / denom
            ent = -(mask[:, :, None] * jnp.exp(logp_all) *
                    logp_all).sum() / denom
            return pg - entropy_coef * ent, (pg, ent)

        opt = self._opt

        @jax.jit
        def update(params, opt_state, b):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, b)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        return update

    # ------------------------------------------------------------ host

    def _pack(self, batch: RolloutBatch) -> Dict[str, Any]:
        import jax.numpy as jnp

        rewards = batch.rewards
        if rewards is None:
            raise ValueError(
                f"batch {batch.batch_id} is unscored (rewards=None)")
        B = batch.num_samples()
        if self._shape is None:
            L = max(len(p) + len(c) for p, c in
                    zip(batch.prompts, batch.completions))
            T = max(max(len(c) for c in batch.completions), 1)
            self._shape = (B, L, T)
        eB, L, T = self._shape
        if B != eB:
            raise ValueError(f"batch size changed: {B} != {eB}")
        tokens = np.zeros((B, L), np.int32)
        gpos = np.zeros((B, T), np.int32)
        targets = np.zeros((B, T), np.int32)
        behavior = np.zeros((B, T), np.float32)
        mask = np.zeros((B, T), np.float32)
        rew_tok = np.zeros((B, T), np.float32)
        dones = np.zeros((B, T), np.float32)
        for i, (p, c, lp) in enumerate(zip(batch.prompts,
                                           batch.completions,
                                           batch.logprobs)):
            if len(lp) != len(c):
                raise ValueError(
                    f"batch {batch.batch_id} sample {i}: {len(lp)} "
                    f"logprobs for {len(c)} tokens — was the engine "
                    f"built with capture_logprobs=True?")
            seq = list(p) + list(c)
            if len(seq) > L or len(c) > T:
                raise ValueError(
                    f"sample {i} exceeds frozen shape (L={L}, T={T})")
            tokens[i, :len(seq)] = seq
            n = len(c)
            gpos[i, :n] = np.arange(len(p) - 1, len(p) - 1 + n)
            targets[i, :n] = c
            behavior[i, :n] = lp
            mask[i, :n] = 1.0
            if n:
                rew_tok[i, n - 1] = rewards[i]
                dones[i, n - 1] = 1.0
        rew = np.asarray(rewards, np.float32)
        base = self.baseline if self.update_count else float(rew.mean())
        adv = rew - base
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        return {
            "tokens": jnp.asarray(tokens),
            "gpos": jnp.asarray(gpos),
            "targets": jnp.asarray(targets),
            "behavior": jnp.asarray(behavior),
            "mask": jnp.asarray(mask),
            "adv": jnp.asarray(adv),
            "rew_tok": jnp.asarray(rew_tok),
            "dones": jnp.asarray(dones),
            "baseline": jnp.float32(base),
        }

    def update(self, batch: RolloutBatch) -> Dict[str, Any]:
        """One policy-gradient step on a scored rollout batch."""
        packed = self._pack(batch)
        # Multiple epochs over the same batch is standard PPO — the
        # clipped ratio (against the FIXED behavior logprobs) is what
        # keeps later epochs from running away from the sampler.
        for _ in range(self.sgd_epochs):
            (self.params, self.opt_state, loss,
             (pg, ent)) = self._update_fn(
                self.params, self.opt_state, packed)
        rew_mean = float(np.mean(batch.rewards))
        beta = self.baseline_beta
        self.baseline = (rew_mean if self.update_count == 0
                         else (1 - beta) * self.baseline +
                         beta * rew_mean)
        self.update_count += 1
        return {
            "update": self.update_count,
            "loss": float(loss),
            "pg_loss": float(pg),
            "entropy": float(ent),
            "reward_mean": rew_mean,
            "baseline": self.baseline,
            "num_tokens": batch.num_tokens(),
        }

    # ----------------------------------------------------------- state

    def get_state(self) -> Dict[str, Any]:
        import jax
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "baseline": self.baseline,
            "update_count": self.update_count,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.baseline = float(state["baseline"])
        self.update_count = int(state["update_count"])
