"""The RLHF loop: generate -> score -> update -> publish -> swap,
with round N+1's decode overlapping round N's learner step.

Topology (Podracer's sebulba split): a generator thread drives the
serving engine on ``LANE_BATCH``; the driver thread scores each batch
with a pluggable reward fn, steps the learner (rl/learner.py), then
publishes the new payload durably (``publish_weights`` — the manifest
checkpoint a restarted generator re-syncs from) and exposes it to the
generator, which installs it via ``swap_weights`` at its NEXT round
boundary under the strictly monotonic generation fence. A swap never
lands mid-round: it would mix policies inside a batch's captured
behavior logprobs.

Staleness is bounded on BOTH sides: the generator blocks before
starting round r until ``r - consumed_round <= staleness_bound``
(it may run at most ``staleness_bound`` rounds ahead — 0 degenerates
to the serialized loop), and the driver re-checks at consumption that
the batch's weights lag the learner by at most ``staleness_bound``
updates, raising ``StalenessViolation`` otherwise (the bound is an
invariant, not a hint).

Exactly-once accounting: batch ids are deterministic per round
(``round-<i>``), every consumed id goes into a ledger committed
atomically WITH the learner state each round
(air/checkpoint_manager.py manifest discipline). Generator death
mid-round regenerates only the unconsumed round (same id, consumed
once); learner death pre-commit loses only the uncommitted round —
resume restores the last complete checkpoint, re-publishes the
recovered params (same bytes => same ``weights_id``, the manifest-hash
property), and the generator re-syncs to exactly the recovered
payload. ``AttemptFence`` (train/chaos.py) keeps a superseded loop
from committing after its replacement starts.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu.air.checkpoint_manager import CheckpointManager
from ray_tpu.rl.learner import RolloutLearner
from ray_tpu.rl.rollout import GeneratorKilled, RolloutGenerator
from ray_tpu.serve.weight_rollout import publish_weights
from ray_tpu.train.chaos import AttemptFence


class StalenessViolation(RuntimeError):
    """A consumed rollout batch lagged the learner by more than the
    staleness bound — the overlap machinery let a stale policy's data
    through, which must never happen silently."""


class DuplicateRollout(RuntimeError):
    """A batch id was consumed twice — the exactly-once ledger caught
    a duplicate (e.g. a resume replaying a committed round)."""


class RLHFLoop:
    def __init__(self, generator: RolloutGenerator,
                 learner: RolloutLearner,
                 reward_fn: Callable[[List[int], List[int]], float],
                 prompts_fn: Callable[[int], Sequence[Sequence[int]]],
                 *, rounds: int = 8, staleness_bound: int = 1,
                 overlap: bool = True,
                 ckpt_dir: str, publish_dir: str,
                 control_dir: Optional[str] = None, attempt: int = 1,
                 keep_last_k: Optional[int] = None,
                 learner_delay_s: float = 0.0,
                 generator_mid_round_hook:
                 Optional[Callable[[int], Any]] = None,
                 learner_kill_hook:
                 Optional[Callable[[int], Any]] = None,
                 max_generator_restarts: int = 2):
        self.generator = generator
        self.learner = learner
        self.reward_fn = reward_fn
        self.prompts_fn = prompts_fn
        self.rounds = int(rounds)
        self.staleness_bound = int(staleness_bound)
        self.overlap = bool(overlap)
        self.ckpt_dir = ckpt_dir
        self.publish_dir = publish_dir
        self.attempt = int(attempt)
        self.keep_last_k = keep_last_k
        self.learner_delay_s = float(learner_delay_s)
        self.generator_mid_round_hook = generator_mid_round_hook
        self.learner_kill_hook = learner_kill_hook
        self.max_generator_restarts = int(max_generator_restarts)
        self._fence = (AttemptFence(control_dir, self.attempt)
                       if control_dir else None)

        self._cond = threading.Condition()
        self._pending: Dict[int, Any] = {}
        self._published = None        # (host_params, weights_id, upd)
        self._consumed_round = -1
        self._gen_error = None        # (exc, round)
        self._abort = False
        self._gen_thread: Optional[threading.Thread] = None
        self.generator_restarts = 0

        self.ledger: List[str] = []
        self.reward_curve: List[float] = []
        self.batch_log: List[Dict[str, Any]] = []
        self.timeline: Dict[int, Dict[str, float]] = {}
        self._t0 = 0.0

    # -------------------------------------------------------- internals

    def _now(self) -> float:
        return time.monotonic() - self._t0

    def _pre_commit(self, step: int) -> None:
        if self._fence is not None:
            self._fence.check()
        if self.learner_kill_hook is not None:
            self.learner_kill_hook(step)

    def _publish(self, update_idx: int):
        """Durably publish the learner's current params; returns
        ``(host_params, weights_id)``. The path carries the attempt so
        a resumed loop republishing the recovered update never
        collides with the dead attempt's directory — the weights_id
        depends only on the bytes, so the recovered payload keeps its
        identity."""
        import jax
        host = jax.device_get(self.learner.params)
        path = os.path.join(self.publish_dir,
                            f"update_{update_idx:05d}_a{self.attempt}")
        _, wid = publish_weights(host, path, step=update_idx,
                                 extra={"update": update_idx})
        return host, wid

    def _sync_generator(self, host, wid: str) -> None:
        cur_gen, cur_wid = self.generator.weights_stamp()
        if cur_wid != wid:
            self.generator.sync_weights(host, weights_id=wid)

    def _tl(self, r: int, **kv: float) -> None:
        self.timeline.setdefault(r, {"round": r}).update(kv)

    # -------------------------------------------------- generator thread

    def _start_generator(self, start_round: int) -> None:
        self._gen_thread = threading.Thread(
            target=self._generator_main, args=(start_round,),
            name="rl-rollout-generator", daemon=True)
        self._gen_thread.start()

    def _generator_main(self, start_round: int) -> None:
        r = start_round
        try:
            while True:
                with self._cond:
                    # Round r samples from weights published after
                    # round r-1-k was consumed => staleness k at
                    # consumption. Allowing r - consumed <= bound + 1
                    # is exactly "lag the learner by <= bound
                    # updates"; bound 0 degenerates to serialized.
                    while (not self._abort and r < self.rounds and
                           r - self._consumed_round >
                           self.staleness_bound + 1):
                        self._cond.wait(0.05)
                    if self._abort or r >= self.rounds:
                        return
                    host, wid, upd = self._published
                # Round boundary: no rollout in flight — the only
                # point a swap cannot mix policies inside a batch.
                self._sync_generator(host, wid)
                t_g0 = self._now()
                batch = self.generator.generate(
                    self.prompts_fn(r), round_idx=r,
                    mid_round_hook=self.generator_mid_round_hook)
                with self._cond:
                    self._pending[r] = (batch, upd)
                    self._tl(r, gen_start=t_g0, gen_end=self._now())
                    self._cond.notify_all()
                r += 1
        except BaseException as e:  # noqa: BLE001 - handed to driver
            with self._cond:
                self._gen_error = (e, r)
                self._cond.notify_all()

    def _await_batch(self, r: int):
        """Block until round ``r``'s batch lands; restart a killed
        generator (bounded) at exactly the unconsumed round —
        deterministic batch ids make the regeneration invisible to the
        ledger except as the single expected consumption."""
        while True:
            with self._cond:
                while r not in self._pending and self._gen_error is None:
                    self._cond.wait(0.1)
                if r in self._pending:
                    return self._pending.pop(r)
                exc, err_round = self._gen_error
                self._gen_error = None
            if (not isinstance(exc, GeneratorKilled) or
                    self.generator_restarts >=
                    self.max_generator_restarts):
                raise exc
            self.generator_restarts += 1
            self._start_generator(err_round)

    # ----------------------------------------------------------- driver

    def _consume(self, r: int, batch, synced_update: int) -> None:
        if batch.batch_id in self.ledger:
            raise DuplicateRollout(
                f"batch {batch.batch_id} already consumed")
        staleness = self.learner.update_count - synced_update
        if staleness > self.staleness_bound:
            raise StalenessViolation(
                f"round {r}: batch generated {staleness} updates "
                f"behind the learner (bound {self.staleness_bound})")
        rewards = [self.reward_fn(p, c)
                   for p, c in zip(batch.prompts, batch.completions)]
        batch.rewards = rewards
        t_l0 = self._now()
        stats = self.learner.update(batch)
        if self.learner_delay_s:
            time.sleep(self.learner_delay_s)
        self.ledger.append(batch.batch_id)
        self.reward_curve.append(stats["reward_mean"])
        self.batch_log.append({
            "batch_id": batch.batch_id, "round": r,
            "weights_id": batch.weights_id,
            "generation": batch.generation,
            "staleness": staleness,
            "reward_mean": stats["reward_mean"],
            "num_tokens": batch.num_tokens(),
        })
        self._tl(r, learn_start=t_l0, learn_end=self._now())

    def _checkpoint(self, mgr: CheckpointManager, r: int,
                    wid: str) -> None:
        mgr.save({
            "learner": self.learner.get_state(),
            "round": r,
            "ledger": list(self.ledger),
            "reward_curve": list(self.reward_curve),
            "batch_log": list(self.batch_log),
            "weights_id": wid,
        }, step=r)

    def run(self) -> Dict[str, Any]:
        mgr = CheckpointManager(self.ckpt_dir,
                                keep_last_k=self.keep_last_k,
                                pre_commit_hook=self._pre_commit)
        start_round = 0
        resumed = False
        recovered_wid = None
        try:
            with contextlib.ExitStack() as stack:
                if self._fence is not None:
                    stack.enter_context(self._fence)
                ckpt = mgr.latest_complete()
                if ckpt is not None:
                    st = ckpt.to_dict()
                    self.learner.set_state(st["learner"])
                    self.ledger = list(st["ledger"])
                    self.reward_curve = list(st["reward_curve"])
                    self.batch_log = list(st["batch_log"])
                    start_round = int(st["round"]) + 1
                    recovered_wid = st["weights_id"]
                    resumed = True
                self._consumed_round = start_round - 1
                self._t0 = time.monotonic()

                # Publish the starting payload (update 0 or the
                # recovered one); the generator syncs to it before its
                # first round. Same bytes => same weights_id, so a
                # resume provably lands back on the recovered payload.
                host, wid = self._publish(self.learner.update_count)
                self._published = (host, wid, self.learner.update_count)
                resync_wid = wid

                if self.overlap:
                    self._start_generator(start_round)
                for r in range(start_round, self.rounds):
                    if self._fence is not None:
                        self._fence.check()
                    if self.overlap:
                        batch, upd = self._await_batch(r)
                    else:
                        host, wid, upd = self._published
                        self._sync_generator(host, wid)
                        t_g0 = self._now()
                        batch = self.generator.generate(
                            self.prompts_fn(r), round_idx=r,
                            mid_round_hook=(
                                self.generator_mid_round_hook))
                        self._tl(r, gen_start=t_g0,
                                 gen_end=self._now())
                    self._consume(r, batch, upd)
                    host, wid = self._publish(self.learner.update_count)
                    self._checkpoint(mgr, r, wid)
                    with self._cond:
                        self._published = (host, wid,
                                           self.learner.update_count)
                        self._consumed_round = r
                        self._cond.notify_all()
                wall = self._now()
        finally:
            with self._cond:
                self._abort = True
                self._cond.notify_all()
            t = self._gen_thread
            if t is not None:
                t.join(timeout=30)
            mgr.close()
        return self._stats(start_round, resumed, recovered_wid,
                           resync_wid, wall)

    # ------------------------------------------------------------ stats

    def _stats(self, start_round: int, resumed: bool,
               recovered_wid: Optional[str], resync_wid: str,
               wall: float) -> Dict[str, Any]:
        tl = [self.timeline[r] for r in sorted(self.timeline)]
        gen_busy = sum(e.get("gen_end", 0.0) - e.get("gen_start", 0.0)
                       for e in tl if "gen_start" in e)
        overlap_observed = any(
            "gen_start" in b and "learn_end" in a and
            b["gen_start"] < a["learn_end"]
            for a, b in zip(tl, tl[1:]))
        return {
            "mode": "overlap" if self.overlap else "serialized",
            "rounds": self.rounds,
            "start_round": start_round,
            "resumed": resumed,
            "recovered_weights_id": recovered_wid,
            "resync_weights_id": resync_wid,
            "reward_curve": list(self.reward_curve),
            "ledger": list(self.ledger),
            "batch_log": list(self.batch_log),
            "staleness_bound": self.staleness_bound,
            "max_staleness": max(
                (b["staleness"] for b in self.batch_log), default=0),
            "generator_restarts": self.generator_restarts,
            "wall_s": wall,
            "gen_busy_s": gen_busy,
            "generator_utilization": gen_busy / max(wall, 1e-9),
            "overlap_observed": overlap_observed,
            "timeline": tl,
            "final_weights_id": self.generator.weights_stamp()[1],
        }
