"""RLHF rollout subsystem: the serving engine as an RL generation
actor (ROADMAP: "engine as an RL rollout generator").

The split follows Podracer's sebulba architecture (arXiv 2104.06272):
a generation side (``RolloutGenerator`` over ``LLMEngine`` /
``EnginePool``, submitting on ``LANE_BATCH`` so co-located online
traffic keeps its SLO) and a learner side (``RolloutLearner``, reusing
the rllib loss pieces), glued by ``RLHFLoop`` which overlaps round
N+1's decode with round N's learner step under PR 19's monotonic
weight-generation fence and a bounded-staleness knob.
"""
from ray_tpu.rl.rollout import (GeneratorKilled, RolloutBatch,
                                RolloutGenerator)
from ray_tpu.rl.learner import RolloutLearner
from ray_tpu.rl.loop import (DuplicateRollout, RLHFLoop,
                             StalenessViolation)

__all__ = [
    "RolloutBatch", "RolloutGenerator", "RolloutLearner", "RLHFLoop",
    "GeneratorKilled", "DuplicateRollout", "StalenessViolation",
]
