"""Additional datasources + sinks.

Capability parity with the reference's datasource set
(python/ray/data/read_api.py:222+ and data/datasource/ — parquet, csv,
json, numpy, binary, text readers; write_* sinks; from_pandas /
to_pandas interconversion).
"""
from __future__ import annotations

import glob as globlib
import os
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.dataset import Dataset, from_items


def _expand(path: str) -> List[str]:
    if os.path.isdir(path):
        return sorted(
            p for p in globlib.glob(os.path.join(path, "*"))
            if os.path.isfile(p))
    return sorted(globlib.glob(path)) or [path]


def read_text(path: str, parallelism: int = 8) -> Dataset:
    """One row per line (reference: read_text)."""
    rows: List[str] = []
    for p in _expand(path):
        with open(p) as f:
            rows.extend(line.rstrip("\n") for line in f)
    return from_items(rows, parallelism)


def read_binary_files(path: str, parallelism: int = 8,
                      include_paths: bool = False) -> Dataset:
    """Whole files as bytes rows (reference: read_binary_files)."""
    rows: List[Any] = []
    for p in _expand(path):
        with open(p, "rb") as f:
            data = f.read()
        rows.append({"path": p, "bytes": data} if include_paths
                    else data)
    return from_items(rows, parallelism)


def read_numpy(path: str, parallelism: int = 8) -> Dataset:
    """.npy files -> rows of {'data': row} (reference: read_numpy)."""
    arrays = [np.load(p) for p in _expand(path)]
    arr = np.concatenate(arrays) if len(arrays) > 1 else arrays[0]
    from ray_tpu.data.dataset import from_numpy
    return from_numpy(arr, parallelism)


def read_parquet(path: str, parallelism: int = 8) -> Dataset:
    """Parquet via pandas/pyarrow; raises a clear ImportError where
    pyarrow is unavailable."""
    try:
        import pyarrow.parquet as pq  # noqa: F401
    except ImportError:
        raise ImportError(
            "read_parquet requires pyarrow, which is not available in "
            "this environment; convert to csv/json/npy or install "
            "pyarrow.") from None
    import pandas as pd
    frames = [pd.read_parquet(p) for p in _expand(path)]
    return from_pandas(pd.concat(frames), parallelism)


def from_pandas(df, parallelism: int = 8) -> Dataset:
    """DataFrame -> dataset of dict rows (reference: from_pandas)."""
    rows = df.to_dict(orient="records")
    return from_items(rows, parallelism)


def to_pandas(ds: Dataset):
    import pandas as pd
    return pd.DataFrame(ds.take_all())


def write_csv(ds: Dataset, path: str) -> str:
    import csv
    rows = ds.take_all()
    if rows and not isinstance(rows[0], dict):
        rows = [{"value": r} for r in rows]
    fields: List[str] = []
    for r in rows:
        for k in r:
            if k not in fields:
                fields.append(k)
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fields)
        w.writeheader()
        w.writerows(rows)
    return path


def write_json(ds: Dataset, path: str) -> str:
    import json
    with open(path, "w") as f:
        for r in ds.take_all():
            f.write(json.dumps(r) + "\n")
    return path


def write_numpy(ds: Dataset, path: str,
                column: Optional[str] = "data") -> str:
    rows = ds.take_all()
    if rows and isinstance(rows[0], dict):
        arr = np.stack([np.asarray(r[column]) for r in rows])
    else:
        arr = np.asarray(rows)
    np.save(path, arr)
    return path


class RandomAccessDataset:
    """O(log n) point lookups on a sorted-by-key dataset (reference:
    python/ray/data/random_access_dataset.py — sorted blocks + binary
    search within the owning block)."""

    def __init__(self, ds: Dataset, key: str):
        self._key = key
        rows = sorted(ds.take_all(), key=lambda r: r[key])
        n_blocks = max(1, ds.num_blocks())
        splits = np.array_split(np.arange(len(rows)), n_blocks)
        self._blocks: List[ray_tpu.ObjectRef] = []
        self._bounds: List[Any] = []   # first key of each block
        for idx in splits:
            if len(idx) == 0:
                continue
            block = [rows[i] for i in idx]
            self._blocks.append(ray_tpu.put(block))
            self._bounds.append(block[0][key])

    def get(self, key_value: Any) -> Optional[Dict[str, Any]]:
        import bisect
        if not self._blocks:
            return None
        i = bisect.bisect_right(self._bounds, key_value) - 1
        if i < 0:
            return None
        block = ray_tpu.get(self._blocks[i])
        lo = bisect.bisect_left([r[self._key] for r in block], key_value)
        if lo < len(block) and block[lo][self._key] == key_value:
            return block[lo]
        return None

    def multiget(self, keys: List[Any]) -> List[Optional[Dict[str, Any]]]:
        return [self.get(k) for k in keys]
