"""Additional datasources + sinks.

Capability parity with the reference's datasource set
(python/ray/data/read_api.py:222+ and data/datasource/ — parquet, csv,
json, numpy, binary, text readers; write_* sinks; from_pandas /
to_pandas interconversion).
"""
from __future__ import annotations

import glob as globlib
import os
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.dataset import Dataset, from_items


def _expand(path: str) -> List[str]:
    if os.path.isdir(path):
        return sorted(
            p for p in globlib.glob(os.path.join(path, "*"))
            if os.path.isfile(p))
    return sorted(globlib.glob(path)) or [path]


@ray_tpu.remote(num_cpus=0.25)
def _read_source_file(p: str, fmt: str, include_paths: bool):
    """Source task: file bytes never pass through the driver
    (reference: read tasks, data/read_api.py)."""
    if fmt == "text":
        with open(p) as f:
            return [line.rstrip("\n") for line in f]
    if fmt == "binary":
        with open(p, "rb") as f:
            data = f.read()
        return [{"path": p, "bytes": data}] if include_paths \
            else [data]
    if fmt == "csv":
        import csv
        rows: List[Any] = []
        with open(p, newline="") as f:
            for row in csv.DictReader(f):
                parsed = {}
                for k, v in row.items():
                    # int first, float fallback: "2"->2, "1E5"->1e5,
                    # "NaN"->nan, else keep the string
                    try:
                        parsed[k] = int(v)
                    except (ValueError, TypeError):
                        try:
                            parsed[k] = float(v)
                        except (ValueError, TypeError):
                            parsed[k] = v
                rows.append(parsed)
        return rows
    if fmt == "jsonl":
        import json
        rows = []
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return rows
    arr = np.load(p)                 # numpy
    return [{"data": row} for row in arr]


def _read_source(path: str, fmt: str, parallelism: int,
                 include_paths: bool = False) -> Dataset:
    paths = _expand(path)
    ds = Dataset([_read_source_file.remote(p, fmt, include_paths)
                  for p in paths])
    if len(paths) < parallelism:
        ds = ds.repartition(parallelism)
    return ds


def read_text(path: str, parallelism: int = 8) -> Dataset:
    """One row per line, one read task per file (reference:
    read_text)."""
    return _read_source(path, "text", parallelism)


def read_binary_files(path: str, parallelism: int = 8,
                      include_paths: bool = False) -> Dataset:
    """Whole files as bytes rows (reference: read_binary_files)."""
    return _read_source(path, "binary", parallelism, include_paths)


def read_numpy(path: str, parallelism: int = 8) -> Dataset:
    """.npy files -> rows of {'data': row} (reference: read_numpy)."""
    return _read_source(path, "numpy", parallelism)


def read_parquet(path: str, parallelism: int = 8) -> Dataset:
    """Parquet via pandas/pyarrow; raises a clear ImportError where
    pyarrow is unavailable."""
    try:
        import pyarrow.parquet as pq  # noqa: F401
    except ImportError:
        raise ImportError(
            "read_parquet requires pyarrow, which is not available in "
            "this environment; convert to csv/json/npy or install "
            "pyarrow.") from None
    import pandas as pd
    frames = [pd.read_parquet(p) for p in _expand(path)]
    return from_pandas(pd.concat(frames), parallelism)


def from_pandas(df, parallelism: int = 8) -> Dataset:
    """DataFrame -> dataset of dict rows (reference: from_pandas)."""
    rows = df.to_dict(orient="records")
    return from_items(rows, parallelism)


def to_pandas(ds: Dataset):
    import pandas as pd
    return pd.DataFrame(ds.take_all())


@ray_tpu.remote(num_cpus=0.25)
def _block_fields(block) -> List[str]:
    """Union of column names in one block, in first-seen order (csv
    schema pass: O(blocks) lists of names return to the driver, never
    rows)."""
    fields: List[str] = []
    for r in block:
        if isinstance(r, dict):
            for k in r:
                if k not in fields:
                    fields.append(k)
        elif "value" not in fields:
            fields.append("value")
    return fields


def _union_fields(ds: Dataset) -> List[str]:
    fields: List[str] = []
    for part in ray_tpu.get([_block_fields.remote(b)
                             for b in ds._block_refs]):
        for k in part:
            if k not in fields:
                fields.append(k)
    return fields


@ray_tpu.remote(num_cpus=0.25)
def _block_field_kinds(block) -> Dict[str, str]:
    """field -> coarse kind ('bool'|'int'|'float'|'str'|'other') for
    the parquet type union (O(blocks) dicts to the driver)."""
    kinds: Dict[str, str] = {}
    order = {"bool": 0, "int": 1, "float": 2, "str": 3, "other": 4}

    def kind_of(v):
        if isinstance(v, bool):
            return "bool"
        if isinstance(v, int):
            return "int"
        if isinstance(v, float):
            return "float"
        if isinstance(v, str):
            return "str"
        return "other"

    for r in _normalize_rows(block):
        for k, v in r.items():
            nk = kind_of(v)
            if k not in kinds or order[nk] > order[kinds[k]]:
                # promotion: bool < int < float < str < other; a
                # mixed int/float column unifies to float, anything
                # with strings to str
                kinds[k] = nk
    return kinds


_PANDAS_DTYPE = {"bool": "boolean", "int": "Int64",
                 "float": "float64", "str": "string"}


def _union_dtypes(ds: Dataset) -> Dict[str, str]:
    """Dataset-wide field -> pandas (nullable) dtype, so every parquet
    part file carries the SAME physical schema: a part missing a
    column writes typed nulls, not NaN-inferred float64."""
    order = {"bool": 0, "int": 1, "float": 2, "str": 3, "other": 4}
    kinds: Dict[str, str] = {}
    for part in ray_tpu.get([_block_field_kinds.remote(b)
                             for b in ds._block_refs]):
        for k, nk in part.items():
            if k not in kinds or order[nk] > order[kinds[k]]:
                kinds[k] = nk
    return {k: _PANDAS_DTYPE[v] for k, v in kinds.items()
            if v in _PANDAS_DTYPE}


def _normalize_rows(block) -> List[Dict[str, Any]]:
    """Record rows pass through; scalar rows wrap as {"value": r}
    (the shared convention across every writer)."""
    if block and isinstance(block[0], dict):
        return block
    return [{"value": r} for r in block]


@ray_tpu.remote(num_cpus=0.25)
def _write_block(block, path: str, fmt: str, column: Optional[str],
                 fields: Optional[List[str]] = None,
                 dtypes: Optional[Dict[str, str]] = None):
    """Sink task: one output file per block (reference: write_* tasks,
    data/_internal write path — rows never pass through the driver)."""
    if fmt == "csv":
        import csv
        rows = _normalize_rows(block)
        with open(path, "w", newline="") as f:
            # one dataset-wide schema: every part file has the same
            # header, so parts concatenate cleanly downstream
            w = csv.DictWriter(f, fieldnames=fields or ["value"],
                               restval="")
            w.writeheader()
            w.writerows(rows)
    elif fmt == "json":
        import json
        with open(path, "w") as f:
            for r in block:
                f.write(json.dumps(r) + "\n")
    elif fmt == "numpy":
        if block and isinstance(block[0], dict):
            arr = np.stack([np.asarray(r[column]) for r in block])
        else:
            arr = np.asarray(block)
        np.save(path, arr)
    elif fmt == "parquet":
        import pandas as pd
        # Dataset-wide column AND dtype union (same stance as csv):
        # every part file carries one physical schema — a part
        # missing a column writes typed nulls, not NaN-cast float64 —
        # so standard parquet dataset readers (pyarrow/Spark/DuckDB)
        # accept the directory.
        df = pd.DataFrame(_normalize_rows(block),
                          columns=fields or None)
        if dtypes:
            df = df.astype(dtypes)
        df.to_parquet(path)
    return path


_EXT = {"csv": "csv", "json": "json", "numpy": "npy",
        "parquet": "parquet"}


def _write(ds: Dataset, path: str, fmt: str,
           column: Optional[str] = None) -> str:
    """Directory path (trailing sep or existing dir) -> one
    ``part-NNNNN.<ext>`` file per block, written by remote tasks in
    parallel. Plain file path -> blocks stream through the driver one
    at a time into a single file (constant driver memory)."""
    dir_mode = path.endswith(os.sep) or os.path.isdir(path)
    ds = ds.materialize()
    fields = _union_fields(ds) if fmt in ("csv", "parquet") \
        else None
    dtypes = _union_dtypes(ds) if fmt == "parquet" else None
    if dir_mode:
        os.makedirs(path, exist_ok=True)
        outs = [_write_block.remote(
                    b, os.path.join(
                        path, f"part-{i:05d}.{_EXT[fmt]}"),
                    fmt, column, fields, dtypes)
                for i, b in enumerate(ds._block_refs)]
        ray_tpu.get(outs)
        return path
    # Single file: stream one block at a time through the driver.
    if fmt == "parquet":
        # One parquet file can't be appended to, so the whole dataset
        # is on the driver either way (use the directory form for
        # datasets larger than driver RAM) — fetch blocks in one
        # batched get rather than serially.
        import pandas as pd
        frames = [pd.DataFrame(_normalize_rows(b), columns=fields)
                  for b in ray_tpu.get(list(ds._block_refs))]
        df = pd.concat(frames, ignore_index=True)
        if dtypes:
            df = df.astype(dtypes)
        df.to_parquet(path)
        return path
    if fmt == "json":
        import json
        with open(path, "w") as f:
            for b in ds._block_refs:
                for r in ray_tpu.get(b):
                    f.write(json.dumps(r) + "\n")
        return path
    if fmt == "csv":
        import csv
        with open(path, "w", newline="") as f:
            # dataset-wide field union (collected as metadata above):
            # no column is ever silently dropped
            w = csv.DictWriter(f, fieldnames=fields or ["value"],
                               restval="")
            w.writeheader()
            for b in ds._block_refs:
                block = ray_tpu.get(b)
                w.writerows(_normalize_rows(block))
        return path
    # numpy: one array file needs the whole array once
    parts = []
    for b in ds._block_refs:
        block = ray_tpu.get(b)
        if block and isinstance(block[0], dict):
            parts.append(np.stack([np.asarray(r[column])
                                   for r in block]))
        elif block:
            parts.append(np.asarray(block))
    np.save(path, np.concatenate(parts) if parts
            else np.asarray([]))
    return path


def write_csv(ds: Dataset, path: str) -> str:
    return _write(ds, path, "csv")


def write_parquet(ds: Dataset, path: str) -> str:
    """Reference: Dataset.write_parquet — one part file per block in
    directory mode, a single file otherwise."""
    try:
        import pyarrow  # noqa: F401
    except ImportError:
        raise ImportError(
            "write_parquet requires pyarrow, which is not available "
            "in this environment; use write_csv/write_json.") \
            from None
    return _write(ds, path, "parquet")


def write_json(ds: Dataset, path: str) -> str:
    return _write(ds, path, "json")


def write_numpy(ds: Dataset, path: str,
                column: Optional[str] = "data") -> str:
    return _write(ds, path, "numpy", column)


# Actor-served key->row store; canonical home is
# ray_tpu/data/random_access.py (re-exported here for back-compat).
from ray_tpu.data.random_access import RandomAccessDataset  # noqa: E402,F401


def from_torch(dataset, parallelism: int = 8) -> Dataset:
    """A torch map-style Dataset -> rows (reference: from_torch).
    Tensors convert to numpy so blocks serialize zero-copy."""
    rows: List[Any] = []
    for i in range(len(dataset)):
        item = dataset[i]
        rows.append(_torchify(item))
    return from_items(rows, parallelism)


def _torchify(item):
    try:
        import torch
        if isinstance(item, torch.Tensor):
            return item.detach().cpu().numpy()
    except ImportError:
        pass
    if isinstance(item, tuple):
        return tuple(_torchify(x) for x in item)
    if isinstance(item, list):
        return [_torchify(x) for x in item]
    if isinstance(item, dict):
        return {k: _torchify(v) for k, v in item.items()}
    return item


def from_huggingface(hf_dataset, parallelism: int = 8) -> Dataset:
    """A huggingface datasets.Dataset -> rows of dicts (reference:
    from_huggingface)."""
    return from_items(list(hf_dataset), parallelism)
