"""Distributed datasets: blocks in the object store, lazy stage plans.

Capability parity with the reference's Dataset core
(python/ray/data/dataset.py:124, blocks _internal/{plan.py,compute.py},
shuffle _internal/push_based_shuffle.py, datasources datasource/*): data
lives as blocks behind ObjectRefs; transforms are lazy stages fused into one
task per block at execution; map_batches supports task- or actor-pool
compute; shuffle/groupby are two-stage all-to-all jobs of remote tasks.

TPU-native addition: ``iter_device_batches(mesh)`` materializes batches
directly as mesh-sharded jax Arrays (the Train ingest path), and
``split(n)`` produces per-worker shards for SPMD gangs.
"""
from __future__ import annotations

import itertools
from typing import (Any, Callable, Dict, Iterator, List, Optional, Tuple,
                    Union)

import numpy as np

import ray_tpu

Block = List[Any]          # a block is a list of rows (or dict-batches)
BatchFormat = Union[List[Any], Dict[str, np.ndarray]]


# --------------------------------------------------------------------------
# Remote block workers
# --------------------------------------------------------------------------

@ray_tpu.remote(num_cpus=0.5)
def _apply_stages(block: Block, stages: Tuple) -> Block:
    for kind, fn in stages:
        block = _apply_one_stage(block, kind, fn)
    return block


def _approx_block_bytes(block: Block) -> int:
    """Cheap shallow payload estimate for stats reporting: exact for
    numpy payloads, length-based for str/bytes, flat 8 bytes per
    other scalar/row. An estimator, not an accountant — stats must
    never cost a serialization pass."""
    total = 0
    for row in block:
        vals = row.values() if isinstance(row, dict) else (row,)
        for v in vals:
            if isinstance(v, np.ndarray):
                total += v.nbytes
            elif isinstance(v, (str, bytes)):
                total += len(v)
            elif isinstance(v, (list, tuple)):
                total += 8 * len(v)
            else:
                total += 8
    return total


@ray_tpu.remote(num_cpus=0.5)
def _apply_stages_timed(block: Block, stages: Tuple):
    """``_apply_stages`` with a per-stage execution report: for each
    stage, rows in/out, approximate output bytes, and wall seconds —
    the payload behind ``Dataset.stats_dict()`` (and the pipeline
    stats the batch tier folds into its progress manifests). Two
    returns: the transformed block, then the stats row."""
    import time as _time
    per_stage = []
    for kind, fn in stages:
        rows_in = len(block)
        t0 = _time.perf_counter()
        block = _apply_one_stage(block, kind, fn)
        per_stage.append({
            "stage": kind,
            "rows_in": rows_in,
            "rows_out": len(block),
            "bytes_out": _approx_block_bytes(block),
            "wall_s": _time.perf_counter() - t0,
        })
    return block, per_stage


def _apply_one_stage(block: Block, kind: str, fn) -> Block:
    if kind == "map":
        return [fn(row) for row in block]
    if kind == "filter":
        return [row for row in block if fn(row)]
    if kind == "flat_map":
        return [out for row in block for out in fn(row)]
    if kind == "map_batches":
        return _apply_map_batches(block, fn)
    raise ValueError(f"unknown stage kind {kind!r}")


def _apply_map_batches(block: Block, spec) -> Block:
    fn, batch_size, batch_format = spec
    out: Block = []
    for i in range(0, len(block), batch_size or len(block) or 1):
        chunk = block[i:i + batch_size] if batch_size else block
        batch = _to_batch(chunk, batch_format)
        res = fn(batch)
        out.extend(_from_batch(res))
        if not batch_size:
            break
    return out


def _to_batch(rows: Block, batch_format: str) -> BatchFormat:
    if batch_format == "numpy" and rows and isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return list(rows)


def _from_batch(batch: BatchFormat) -> Block:
    if isinstance(batch, dict):
        keys = list(batch.keys())
        n = len(batch[keys[0]])
        return [{k: batch[k][i] for k in keys} for i in range(n)]
    return list(batch)


def _key_getter(key):
    if key is None:
        return lambda r: r
    return key if callable(key) else (lambda r: r[key])


# --------------------------------------------------------------------------
# Shuffle / reorganization task graph (reference shape:
# python/ray/data/_internal/push_based_shuffle.py — map tasks partition
# each input block, reduce tasks merge one partition from every map task;
# no row ever passes through the driver, only O(blocks) metadata does).
# --------------------------------------------------------------------------

@ray_tpu.remote(num_cpus=0.25)
def _block_len(block: Block) -> int:
    return len(block)


@ray_tpu.remote(num_cpus=0.25)
def _block_sum(block: Block, key):
    getter = _key_getter(key)
    return sum(getter(r) for r in block) if block else 0


@ray_tpu.remote(num_cpus=0.25)
def _block_moments(block: Block, key):
    """(count, mean, M2) partials for std() — centered second moment
    per block avoids catastrophic cancellation at large means."""
    getter = _key_getter(key)
    vals = np.asarray([float(getter(r)) for r in block], np.float64)
    if vals.size == 0:
        return (0, 0.0, 0.0)
    mean = float(vals.mean())
    return (int(vals.size), mean, float(((vals - mean) ** 2).sum()))


@ray_tpu.remote(num_cpus=0.25)
def _sample_block(block: Block, fraction: float, seed: int,
                  block_idx: int) -> Block:
    rng = np.random.RandomState((seed + block_idx) & 0x7FFFFFFF)
    keep = rng.random_sample(len(block)) < fraction
    return [r for r, k in zip(block, keep) if k]


@ray_tpu.remote(num_cpus=0.25)
def _truncate_block(block: Block, k: int) -> Block:
    return block[:k]


@ray_tpu.remote(num_cpus=0.25)
def _block_unique(block: Block, key) -> List[Any]:
    getter = _key_getter(key)
    seen, out = set(), []
    for row in block:
        v = getter(row)
        if v not in seen:
            seen.add(v)
            out.append(v)
    return out


@ray_tpu.remote(num_cpus=0.25)
def _block_extreme(block: Block, key, lo: bool):
    # (has_value, value) — None is a legal extreme value, so an empty
    # block needs a distinct sentinel.
    getter = _key_getter(key)
    vals = [getter(r) for r in block]
    if not vals:
        return (False, None)
    import builtins
    return (True, builtins.min(vals) if lo else builtins.max(vals))


@ray_tpu.remote(num_cpus=0.25)
def _block_np(block: Block, key):
    if key is not None:
        return np.asarray([r[key] for r in block])
    return np.asarray(block)


@ray_tpu.remote(num_cpus=0.25)
def _slice_block(block: Block, cuts: List[Tuple[int, int]]):
    """Map side of a range repartition: slice this block into the
    per-output-partition row ranges computed from global offsets."""
    out = tuple(block[s:e] for (s, e) in cuts)
    return out if len(out) > 1 else out[0]


@ray_tpu.remote(num_cpus=0.25)
def _concat_parts(*parts: Block) -> Block:
    return [row for p in parts for row in p]


@ray_tpu.remote(num_cpus=0.25)
def _sample_keys(block: Block, key, k: int) -> List[Any]:
    getter = _key_getter(key)
    if not block:
        return []
    idx = np.linspace(0, len(block) - 1, num=min(k, len(block)),
                      dtype=int)
    return [getter(block[i]) for i in idx]


@ray_tpu.remote(num_cpus=0.25)
def _range_partition(block: Block, key, bounds: List[Any]):
    """Map side of sample-sort: bucket rows by the sampled boundaries.
    Each bucket is pre-sorted so the reduce side can merge cheaply."""
    getter = _key_getter(key)
    n_out = len(bounds) + 1
    buckets: List[Block] = [[] for _ in range(n_out)]
    import bisect
    for row in block:
        buckets[bisect.bisect_right(bounds, getter(row))].append(row)
    for b in buckets:
        b.sort(key=getter)
    return tuple(buckets) if n_out > 1 else buckets[0]


@ray_tpu.remote(num_cpus=0.25)
def _sorted_merge(key, descending: bool, *parts: Block) -> Block:
    import heapq
    getter = _key_getter(key)
    merged = list(heapq.merge(*parts, key=getter))
    if descending:
        merged.reverse()
    return merged


def _stable_hash(v: Any) -> int:
    """Process-independent, type-insensitive hash: Python's ``hash()``
    is randomized per interpreter for str/bytes (which would route equal
    keys to different partitions on different distributed workers), and
    numerically equal keys (1, 1.0, np.int64(1)) must land in the same
    partition or the reducer emits duplicate groups."""
    import zlib
    if isinstance(v, (bool, np.bool_, int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        f = float(v)
        return int(f) if f.is_integer() \
            else zlib.crc32(repr(f).encode())
    if isinstance(v, str):
        return zlib.crc32(v.encode())
    if isinstance(v, bytes):
        return zlib.crc32(v)
    if isinstance(v, tuple):
        h = 0
        for e in v:
            h = zlib.crc32(repr(_stable_hash(e)).encode(), h)
        return h
    return zlib.crc32(repr(v).encode())


@ray_tpu.remote(num_cpus=0.25)
def _hash_partition(block: Block, key, n_out: int):
    getter = _key_getter(key)
    buckets: List[Block] = [[] for _ in range(n_out)]
    for row in block:
        buckets[_stable_hash(getter(row)) % n_out].append(row)
    return tuple(buckets) if n_out > 1 else buckets[0]


@ray_tpu.remote(num_cpus=0.25)
def _group_and_agg(key, agg_fn, *parts: Block) -> Block:
    """Reduce side of groupby: rows are emitted wrapped with their
    group key ({"__gkey", "row"}) so the follow-up global sort can
    order ANY aggregate row type by group key."""
    getter = _key_getter(key)
    groups: Dict[Any, List[Any]] = {}
    for p in parts:
        for row in p:
            groups.setdefault(getter(row), []).append(row)
    return [{"__gkey": k, "row": agg_fn(k, rows)}
            for k, rows in groups.items()]


@ray_tpu.remote(num_cpus=0.25)
def _strip_gkey(block: Block) -> Block:
    return [r["row"] for r in block]


def _gkey_sortable(r) -> Tuple:
    """Total order over group keys that never raises on mixed types:
    numbers order numerically in one class; other types order within
    their type name (cross-type decided by the name)."""
    k = r["__gkey"]
    if isinstance(k, (bool, int, float, np.integer, np.floating)):
        return (0, "", float(k))
    if isinstance(k, str):
        return (1, "str", k)
    return (1, type(k).__name__, repr(k))


@ray_tpu.remote(num_cpus=0.25)
def _zip_ranges(n_left: int, *parts: Block) -> Block:
    """Reduce side of zip: first ``n_left`` parts are row-aligned slices
    of the left dataset, the rest of the right; concatenating each side
    in block order reconstructs the same global row range."""
    a = [row for p in parts[:n_left] for row in p]
    b = [row for p in parts[n_left:] for row in p]
    import builtins
    rows = []
    for x, y in builtins.zip(a, b):
        if isinstance(x, dict) and isinstance(y, dict):
            merged = dict(x)
            for k, v in y.items():
                merged[k if k not in merged else f"{k}_1"] = v
            rows.append(merged)
        else:
            rows.append((x, y))
    return rows


def _even_cuts(total: int, n_out: int) -> List[Tuple[int, int]]:
    """Global [start, end) row ranges for ``n_out`` near-equal output
    partitions (matches np.array_split sizing)."""
    sizes = [total // n_out + (1 if i < total % n_out else 0)
             for i in range(n_out)]
    cuts, off = [], 0
    for s in sizes:
        cuts.append((off, off + s))
        off += s
    return cuts


def _slice_plan(block_lens: List[int],
                out_cuts: List[Tuple[int, int]]
                ) -> List[List[Tuple[int, int]]]:
    """For each input block, the local (start, end) slice that lands in
    each output partition (empty slices allowed)."""
    plans = []
    off = 0
    for blen in block_lens:
        lo, hi = off, off + blen
        plans.append([(max(s, lo) - lo, max(min(e, hi), lo) - lo)
                      for (s, e) in out_cuts])
        off = hi
    return plans


def _fan_out(task, n_out: int, block_refs: List["ray_tpu.ObjectRef"],
             per_block_args=None, shared_args: Tuple = ()) -> List[List]:
    """Launch the map side of a shuffle: one ``task`` per input block
    with ``num_returns=n_out``. Returns, per input block, the list of
    per-output-partition part refs."""
    bound = task.options(num_returns=n_out)
    all_parts = []
    for i, ref in enumerate(block_refs):
        args = (per_block_args[i],) if per_block_args is not None \
            else shared_args
        parts = bound.remote(ref, *args)
        all_parts.append([parts] if n_out == 1 else list(parts))
    return all_parts


def _shuffle_slices(block_refs: List["ray_tpu.ObjectRef"],
                    block_lens: List[int],
                    out_cuts: List[Tuple[int, int]]) -> List[List]:
    """Launch the map side: one slice task per input block; returns, per
    input block, the list of per-output-partition part refs."""
    plans = _slice_plan(block_lens, out_cuts)
    return _fan_out(_slice_block, len(out_cuts), block_refs,
                    per_block_args=plans)


# Input-block count above which the all-to-all ops switch from the
# simple pull shuffle (N maps x num_returns=N, then N reduces over N
# args = O(N^2) live intermediate objects) to the push-based pipeline.
PUSH_SHUFFLE_THRESHOLD = 32
_PUSH_ROUND = 16         # map tasks per pipelined round


@ray_tpu.remote(num_cpus=0.25)
def _random_split(block: Block, seed_i: int, n: int):
    """Map side of random_shuffle (both strategies): split one block
    into n random parts. Seed convention: base + input-block index."""
    rng = np.random.RandomState(seed_i)
    perm = rng.permutation(len(block))
    parts = np.array_split(perm, n)
    out = [[block[i] for i in part] for part in parts]
    return tuple(out) if n > 1 else out[0]


@ray_tpu.remote(num_cpus=0.25)
def _perm_merge(seed_j: int, *parts: Block) -> Block:
    """Reduce side (pull strategy): concat + output permutation.
    Seed convention: base + output index + 10000."""
    merged = [row for p in parts for row in p]
    rng = np.random.RandomState(seed_j + 10000)
    perm = rng.permutation(len(merged))
    return [merged[i] for i in perm]


@ray_tpu.remote(num_cpus=0.25)
def _perm_finalize(seed_j: int, merged: Block) -> Block:
    """Push-strategy finalize: same output permutation as _perm_merge
    applied to the already-folded partition (seed conventions MUST
    stay in lockstep so both strategies shuffle identically)."""
    rng = np.random.RandomState(seed_j + 10000)
    perm = rng.permutation(len(merged))
    return [merged[i] for i in perm]


@ray_tpu.remote(num_cpus=0.25)
def _fold_concat(accum: Optional[Block], *parts: Block) -> Block:
    """Merge-side accumulator of the push shuffle: folds one round's
    parts for one output partition into the running merged block
    (order-preserving concat)."""
    out = list(accum) if accum else []
    for p in parts:
        out.extend(p)
    return out


def _pipelined_all_to_all(block_refs: List["ray_tpu.ObjectRef"],
                          launch_map, n_out: int,
                          fold=None,
                          round_size: int = _PUSH_ROUND) -> List:
    """Push-based shuffle executor (reference:
    python/ray/data/_internal/push_based_shuffle.py — map outputs are
    merged INCREMENTALLY by merge tasks instead of all N x M parts
    staying live until one big reduce).

    launch_map(i, ref) -> list of n_out per-partition part refs for
    input block i. Maps launch in rounds of `round_size`; after each
    round, every output partition folds that round's parts into its
    accumulator block, so at most O(round_size x n_out) intermediate
    objects are in flight — the part refs drop as each fold is
    submitted and the eager-GC frees them as the folds complete. The
    returned accumulators preserve input-block order (fold is an
    ordered concat), so ordered ops (repartition) reuse this path.
    """
    fold = fold or _fold_concat
    accums: List = [None] * n_out
    for start in range(0, len(block_refs), round_size):
        chunk = block_refs[start:start + round_size]
        parts = [launch_map(start + i, r)
                 for i, r in enumerate(chunk)]
        for j in range(n_out):
            col = [p[j] for p in parts]
            accums[j] = fold.remote(accums[j], *col)
        del parts        # refs drop -> freed as folds consume them
    return accums


class _BatchActor:
    """Actor-pool compute for map_batches (reference:
    _internal/compute.py ActorPoolStrategy)."""

    def __init__(self, fn_constructor: Optional[Callable] = None):
        self.fn = fn_constructor() if fn_constructor else None

    def apply(self, block: Block, stages: Tuple) -> Block:
        for kind, spec in stages:
            if kind == "map_batches_actor":
                fn, batch_size, batch_format = spec
                target = self.fn if self.fn is not None else fn
                block = _apply_map_batches(
                    block, (target, batch_size, batch_format))
        return block


# --------------------------------------------------------------------------
# Dataset
# --------------------------------------------------------------------------

class Dataset:
    def __init__(self, block_refs: List[ray_tpu.ObjectRef],
                 stages: Tuple = ()):
        from ray_tpu._private.usage_stats import record_library_usage
        record_library_usage("data")
        self._block_refs = list(block_refs)
        self._stages = tuple(stages)

    # --- lazy transforms --------------------------------------------------

    def _with_stage(self, stage) -> "Dataset":
        return Dataset(self._block_refs, self._stages + (stage,))

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._with_stage(("map", fn))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._with_stage(("filter", fn))

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        return self._with_stage(("flat_map", fn))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = 256,
                    batch_format: str = "default",
                    compute: str = "tasks",
                    num_actors: int = 2,
                    fn_constructor: Optional[Callable] = None
                    ) -> "Dataset":
        if compute == "tasks":
            return self._with_stage(
                ("map_batches", (fn, batch_size, batch_format)))
        # Actor-pool compute executes eagerly over materialized blocks.
        ds = self.materialize()
        actor_cls = ray_tpu.remote(_BatchActor)
        actors = [actor_cls.remote(fn_constructor)
                  for _ in range(num_actors)]
        stage = (("map_batches_actor", (fn, batch_size, batch_format)),)
        refs = []
        for i, block_ref in enumerate(ds._block_refs):
            actor = actors[i % num_actors]
            refs.append(actor.apply.remote(block_ref, stage))
        blocks = ray_tpu.get(refs)
        for a in actors:
            ray_tpu.kill(a)
        return Dataset([ray_tpu.put(b) for b in blocks])

    # --- execution --------------------------------------------------------

    def materialize(self, *, collect_stats: bool = False) -> "Dataset":
        """Execute pending stages as one task per block. The transformed
        blocks stay in the object store as the task outputs — they are
        never pulled into (or re-serialized from) the driver, so
        downstream shuffle ops keep their no-driver-rows guarantee even
        with lazy stages pending. Stage errors surface at first get.

        ``collect_stats=True`` runs the timed execution path: each
        block task also returns a per-stage report (rows in/out,
        approximate bytes, wall seconds) that ``stats_dict()``
        aggregates — the shape the batch tier embeds in its progress
        manifests. Off by default: stats cost one extra ObjectRef per
        block."""
        if not self._stages:
            return self
        import time as _time
        t0 = _time.perf_counter()
        stat_refs = None
        if collect_stats:
            timed = _apply_stages_timed.options(num_returns=2)
            refs, stat_refs = [], []
            for b in self._block_refs:
                block_ref, stat_ref = timed.remote(b, self._stages)
                refs.append(block_ref)
                stat_refs.append(stat_ref)
            out = Dataset(refs)
        else:
            out = Dataset([_apply_stages.remote(b, self._stages)
                           for b in self._block_refs])
        out._exec_stats = {
            "stages": [k for k, _ in self._stages],
            "num_blocks": len(self._block_refs),
            "submit_s": round(_time.perf_counter() - t0, 4),
        }
        if stat_refs is not None:
            out._stage_stat_refs = stat_refs
        return out

    def stats_dict(self) -> Optional[Dict[str, Any]]:
        """Aggregated per-stage execution report from the last
        ``materialize(collect_stats=True)``: for each stage, total
        rows in/out, approximate output bytes, and summed wall
        seconds across block tasks. None when the dataset was not
        executed with stats collection (the cheap default path).
        Fetching barriers on the block tasks — stats describe a
        finished execution, not a plan."""
        refs = getattr(self, "_stage_stat_refs", None)
        if refs is None:
            return None
        per_block = ray_tpu.get(list(refs))
        agg: List[Dict[str, Any]] = []
        for reports in per_block:
            for i, rpt in enumerate(reports):
                if i >= len(agg):
                    agg.append({"stage": rpt["stage"], "rows_in": 0,
                                "rows_out": 0, "bytes_out": 0,
                                "wall_s": 0.0})
                agg[i]["rows_in"] += rpt["rows_in"]
                agg[i]["rows_out"] += rpt["rows_out"]
                agg[i]["bytes_out"] += rpt["bytes_out"]
                agg[i]["wall_s"] += rpt["wall_s"]
        for row in agg:
            row["wall_s"] = round(row["wall_s"], 4)
        return {"stages": agg,
                "num_blocks": len(per_block),
                "submit_s": getattr(self, "_exec_stats",
                                    {}).get("submit_s")}

    def stats(self) -> str:
        """Execution summary (reference: Dataset.stats() — per-stage
        execution report). Lazy datasets report the pending plan;
        materialized ones the last execution's shape; block sizes are
        fetched on demand (one len() task per block)."""
        lines = [f"Dataset(num_blocks={len(self._block_refs)}, "
                 f"pending_stages={[k for k, _ in self._stages]})"]
        ex = getattr(self, "_exec_stats", None)
        if ex:
            lines.append(
                f"  last execution: stages={ex['stages']} over "
                f"{ex['num_blocks']} blocks, submit {ex['submit_s']}s")
        if getattr(self, "_stage_stat_refs", None) is not None:
            sd = self.stats_dict()
            for row in sd["stages"]:
                lines.append(
                    f"  stage {row['stage']}: {row['rows_in']} -> "
                    f"{row['rows_out']} rows, ~{row['bytes_out']} B, "
                    f"{row['wall_s']}s")
        if not self._stages:
            # Row counts only for executed datasets: counting the
            # INPUT blocks of a pending filter/flat_map would report
            # a number the transform will change (and a stats() call
            # must not silently barrier on a pending execution).
            try:
                lens = ray_tpu.get([_block_len.remote(r)
                                    for r in self._block_refs],
                                   timeout=60)
                total = sum(lens)
                lines.append(
                    f"  rows: {total} total; per-block min/mean/max ="
                    f" {min(lens)}/{total / max(len(lens), 1):.1f}/"
                    f"{max(lens)}")
            except Exception:
                pass   # blocks still executing: plan-only report
        return "\n".join(lines)

    def _resolved_blocks(self) -> List[Block]:
        ds = self.materialize()
        return ray_tpu.get(list(ds._block_refs))

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        ds = self.materialize()
        for ref in ds._block_refs:
            out.extend(ray_tpu.get(ref))
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Any]:
        return [row for b in self._resolved_blocks() for row in b]

    def count(self) -> int:
        ds = self.materialize()
        return sum(ray_tpu.get([_block_len.remote(r)
                                for r in ds._block_refs]))

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def sum(self, key: Optional[Union[str, Callable]] = None):
        """Per-block partial sums as remote tasks; only the scalar
        partials return to the driver."""
        ds = self.materialize()
        partials = ray_tpu.get([_block_sum.remote(b, key)
                                for b in ds._block_refs])
        return sum(partials)

    def mean(self, key: Optional[Union[str, Callable]] = None):
        n = self.count()
        return self.sum(key) / n if n else float("nan")

    # --- reorganization ---------------------------------------------------
    # All reorganization ops below run as two-stage task graphs (map:
    # slice/partition each block, reduce: merge one partition from every
    # block). The driver only ever sees O(blocks) ints of metadata —
    # never rows — so datasets larger than driver RAM reorganize fine.

    def _block_lengths(self) -> Tuple["Dataset", List[int]]:
        ds = self.materialize()
        lens = ray_tpu.get([_block_len.remote(b)
                            for b in ds._block_refs])
        return ds, lens

    def split_oversized_blocks(
            self, target_max_block_size: int, *,
            collect_stats: bool = False) -> "Dataset":
        """Cap block size at ``target_max_block_size`` rows: each
        oversized block is sliced (remotely — rows never visit the
        driver) into near-equal parts under the cap; conforming
        blocks pass through by reference, untouched. Unlike
        ``repartition`` this never merges or moves rows across
        blocks, so it is cheap on mostly-conforming data — the map-
        boundary guard the pipeline uses so one skewed source block
        can't become one giant downstream burst
        (``DatasetPipeline.map_batches(target_max_block_size=...)``,
        the batch tier's prefill-burst bound).

        ``collect_stats=True`` runs any pending stages on the timed
        execution path and carries the per-stage report through the
        split, so ``stats_dict()`` still describes the execution even
        though splitting rebuilt the block list — without it, a
        downstream ``materialize(collect_stats=True)`` would see no
        pending stages and report nothing."""
        if target_max_block_size < 1:
            raise ValueError(
                f"target_max_block_size must be >= 1, got "
                f"{target_max_block_size}")
        ds = self.materialize(collect_stats=collect_stats)
        lens = ray_tpu.get([_block_len.remote(b)
                            for b in ds._block_refs])
        if all(n <= target_max_block_size for n in lens):
            return ds
        refs: List[ray_tpu.ObjectRef] = []
        for ref, n in zip(ds._block_refs, lens):
            if n <= target_max_block_size:
                refs.append(ref)
                continue
            k = -(-n // target_max_block_size)
            cuts = _even_cuts(n, k)
            parts = _slice_block.options(
                num_returns=k).remote(ref, cuts)
            refs.extend(parts if isinstance(parts, list)
                        else [parts])
        out = Dataset(refs)
        if getattr(ds, "_stage_stat_refs", None) is not None:
            out._stage_stat_refs = ds._stage_stat_refs
        if getattr(ds, "_exec_stats", None):
            out._exec_stats = ds._exec_stats
        return out

    def repartition(self, num_blocks: int,
                    strategy: str = "auto") -> "Dataset":
        ds, lens = self._block_lengths()
        cuts = _even_cuts(sum(lens), num_blocks)
        if strategy == "push" or (
                strategy == "auto" and
                len(ds._block_refs) > PUSH_SHUFFLE_THRESHOLD):
            # Large job: pipelined push shuffle — O(round x out)
            # live intermediates instead of O(blocks x out).
            plans = _slice_plan(lens, cuts)
            slicer = _slice_block.options(num_returns=len(cuts))

            def launch(i, ref):
                parts = slicer.remote(ref, plans[i])
                return [parts] if len(cuts) == 1 else list(parts)

            return Dataset(_pipelined_all_to_all(
                ds._block_refs, launch, len(cuts)))
        all_parts = _shuffle_slices(ds._block_refs, lens, cuts)
        merged = [_concat_parts.remote(*[parts[j] for parts in all_parts])
                  for j in range(num_blocks)]
        return Dataset(merged)

    def random_shuffle(self, seed: Optional[int] = None,
                       strategy: str = "auto") -> "Dataset":
        """Two-stage all-to-all shuffle (reference:
        _internal/push_based_shuffle.py shape): stage 1 splits each block
        into N random parts; stage 2 merges part i of every block.
        Above PUSH_SHUFFLE_THRESHOLD input blocks (or with
        strategy="push") the merge side runs as the pipelined push
        shuffle, then applies the final per-partition permutation."""
        ds = self.materialize()
        n = max(1, len(ds._block_refs))
        if seed is None:
            # An unseeded shuffle must actually vary call-to-call.
            import os
            seed = int.from_bytes(os.urandom(4), "little")
        if strategy == "push" or (
                strategy == "auto" and n > PUSH_SHUFFLE_THRESHOLD):
            return ds._random_shuffle_push(seed, n)
        base = seed
        splitter = _random_split.options(num_returns=n)
        all_parts = [splitter.remote(b, base + i, n)
                     for i, b in enumerate(ds._block_refs)]
        if n == 1:
            all_parts = [[p] for p in all_parts]
        merged = [_perm_merge.remote(base + j,
                                     *[parts[j] for parts in all_parts])
                  for j in range(n)]
        return Dataset(merged)

    def _random_shuffle_push(self, seed: Optional[int],
                             n: int) -> "Dataset":
        base = seed if seed is not None else 0
        splitter = _random_split.options(num_returns=n)

        def launch(i, ref):
            parts = splitter.remote(ref, base + i, n)
            return [parts] if n == 1 else list(parts)

        accums = _pipelined_all_to_all(self._block_refs, launch, n)
        return Dataset([_perm_finalize.remote(base + j, a)
                        for j, a in enumerate(accums)])

    def sort(self, key: Optional[Union[str, Callable]] = None,
             descending: bool = False) -> "Dataset":
        """Distributed sample-sort: sample boundary keys from each block,
        range-partition every block by those boundaries (map tasks, each
        bucket pre-sorted), then k-way merge each range (reduce tasks).
        Only the boundary samples pass through the driver."""
        ds = self.materialize()
        n = max(1, len(ds._block_refs))
        samples: List[Any] = []
        for s in ray_tpu.get([_sample_keys.remote(b, key, 4 * n)
                              for b in ds._block_refs]):
            samples.extend(s)
        samples.sort()
        if samples and n > 1:
            idx = [len(samples) * (j + 1) // n for j in range(n - 1)]
            bounds = [samples[min(i, len(samples) - 1)] for i in idx]
        else:
            bounds = []
        n_out = len(bounds) + 1
        all_parts = _fan_out(_range_partition, n_out, ds._block_refs,
                             shared_args=(key, bounds))
        order = range(n_out - 1, -1, -1) if descending else range(n_out)
        merged = [_sorted_merge.remote(
                      key, descending,
                      *[parts[j] for parts in all_parts])
                  for j in order]
        return Dataset(merged)

    def groupby(self, key: Union[str, Callable]) -> "GroupedDataset":
        return GroupedDataset(self, key)

    def split(self, n: int) -> List["Dataset"]:
        """Per-worker shards (equal row counts ±1), built with the same
        map/reduce slice graph as repartition — no driver materialize."""
        ds, lens = self._block_lengths()
        cuts = _even_cuts(sum(lens), n)
        all_parts = _shuffle_slices(ds._block_refs, lens, cuts)
        return [Dataset([_concat_parts.remote(
                    *[parts[j] for parts in all_parts])])
                for j in range(n)]

    def train_test_split(self, test_size: Union[int, float], *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> Tuple["Dataset", "Dataset"]:
        """(train, test) split by global row cut (reference:
        Dataset.train_test_split). Same map/reduce slice graph as
        split() — rows never visit the driver."""
        ds = self.random_shuffle(seed=seed) if shuffle else self
        ds, lens = ds._block_lengths()
        total = sum(lens)
        n_test = int(total * test_size) if isinstance(test_size, float) \
            else int(test_size)
        if not 0 <= n_test <= total:
            raise ValueError(
                f"test_size {test_size} out of range for {total} rows")
        cuts = [(0, total - n_test), (total - n_test, total)]
        all_parts = _shuffle_slices(ds._block_refs, lens, cuts)
        return tuple(
            Dataset([_concat_parts.remote(
                *[parts[j] for parts in all_parts])])
            for j in range(2))

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference: Dataset.random_sample),
        one task per block with a per-block-index derived seed so
        blocks draw independent sequences."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0,1]: {fraction}")
        import os
        if seed is None:
            seed = int.from_bytes(os.urandom(4), "little")
        ds = self.materialize()
        return Dataset([_sample_block.remote(b, fraction, seed, i)
                        for i, b in enumerate(ds._block_refs)])

    def std(self, key: Optional[Union[str, Callable]] = None,
            ddof: int = 1) -> float:
        """Sample standard deviation via per-block (count, mean, M2)
        partials merged with Chan's pairwise update — no
        sum-of-squares cancellation (reference: Dataset.std)."""
        ds = self.materialize()
        parts = ray_tpu.get([_block_moments.remote(b, key)
                             for b in ds._block_refs])
        n, mean, m2 = 0, 0.0, 0.0
        for bn, bmean, bm2 in parts:
            if bn == 0:
                continue
            delta = bmean - mean
            tot = n + bn
            mean += delta * bn / tot
            m2 += bm2 + delta * delta * n * bn / tot
            n = tot
        if n - ddof <= 0:
            return float("nan")
        return float((m2 / (n - ddof)) ** 0.5)

    # --- column ops over record rows --------------------------------------

    def add_column(self, name: str,
                   fn: Callable[[Dict[str, Any]], Any]) -> "Dataset":
        """Reference: Dataset.add_column — derive a new field per row."""
        def add(row):
            out = dict(row)
            out[name] = fn(row)
            return out
        return self.map(add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        drop = set(cols)
        return self.map(lambda row: {k: v for k, v in row.items()
                                     if k not in drop})

    def select_columns(self, cols: List[str]) -> "Dataset":
        keep = list(cols)
        return self.map(lambda row: {k: row[k] for k in keep})

    def to_random_access(self, key: Union[str, Callable], *,
                         num_workers: int = 2):
        """Serve this dataset as a key->row store: sorted by ``key``,
        pinned across accessor actors, O(log n) routed lookups
        (reference: Dataset.to_random_access_dataset ->
        random_access_dataset.py)."""
        from ray_tpu.data.random_access import RandomAccessDataset
        sorted_ds = self.sort(key).materialize()
        return RandomAccessDataset(sorted_ds, key,
                                   num_workers=num_workers,
                                   _sorted=True)

    def window(self, *, blocks_per_window: int = 2):
        """Streaming windows (reference: Dataset.window ->
        DatasetPipeline)."""
        import builtins
        from ray_tpu.data.pipeline import DatasetPipeline
        blocks = self._block_refs
        stages = self._stages
        windows = [Dataset(blocks[i:i + blocks_per_window], stages)
                   for i in builtins.range(0, len(blocks),
                                           blocks_per_window)]
        return DatasetPipeline.from_windows(windows)

    def repeat(self, times: Optional[int] = None):
        """Epoch repetition (reference: Dataset.repeat)."""
        return self.window(
            blocks_per_window=max(1, len(self._block_refs))
        ).repeat(times)

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-wise zip as a task graph: both sides are sliced to the
        same global row ranges (map), each range zipped remotely
        (reduce). Rows never visit the driver."""
        a, a_lens = self._block_lengths()
        b, b_lens = other._block_lengths()
        if sum(a_lens) != sum(b_lens):
            raise ValueError(
                f"zip() requires equal lengths, "
                f"got {sum(a_lens)} vs {sum(b_lens)}")
        n_out = max(1, self.num_blocks())
        cuts = _even_cuts(sum(a_lens), n_out)
        a_parts = _shuffle_slices(a._block_refs, a_lens, cuts)
        b_parts = _shuffle_slices(b._block_refs, b_lens, cuts)
        out = []
        for j in range(n_out):
            left = [parts[j] for parts in a_parts]
            right = [parts[j] for parts in b_parts]
            out.append(_zip_ranges.remote(len(left), *left, *right))
        return Dataset(out)

    def limit(self, n: int) -> "Dataset":
        """Keep the first ``n`` rows by truncating blocks remotely —
        lengths are fetched incrementally and blocks beyond the cutoff
        are never touched."""
        ds = self.materialize()
        out, remaining = [], n
        refs = ds._block_refs
        chunk = 64          # batch length fetches; stop at the cutoff
        for i in range(0, len(refs), chunk):
            if remaining <= 0:
                break
            batch = refs[i:i + chunk]
            lens = ray_tpu.get([_block_len.remote(r) for r in batch])
            for ref, blen in zip(batch, lens):
                if remaining <= 0:
                    break
                if blen <= remaining:
                    out.append(ref)
                    remaining -= blen
                else:
                    out.append(_truncate_block.remote(ref, remaining))
                    remaining = 0
        return Dataset(out or [ray_tpu.put([])])

    def unique(self, key: Optional[Union[str, Callable]] = None
               ) -> List[Any]:
        """Per-block remote dedup, then a first-seen-order merge of the
        (already-deduped) partials in the driver."""
        ds = self.materialize()
        seen: set = set()
        merged: List[Any] = []
        for part in ray_tpu.get([_block_unique.remote(b, key)
                                 for b in ds._block_refs]):
            for v in part:
                if v not in seen:
                    seen.add(v)
                    merged.append(v)
        return merged

    def _extreme(self, key, reducer):
        import builtins
        ds = self.materialize()
        lo = reducer is builtins.min
        parts = [v for has, v in ray_tpu.get(
                     [_block_extreme.remote(b, key, lo)
                      for b in ds._block_refs]) if has]
        if not parts:
            raise ValueError("min()/max() of an empty dataset")
        return reducer(parts)

    def min(self, key: Optional[Union[str, Callable]] = None):
        import builtins
        return self._extreme(key, builtins.min)

    def max(self, key: Optional[Union[str, Callable]] = None):
        import builtins
        return self._extreme(key, builtins.max)

    def to_pandas(self):
        from ray_tpu.data.datasources import to_pandas
        return to_pandas(self)

    def write_csv(self, path: str) -> str:
        from ray_tpu.data.datasources import write_csv
        return write_csv(self, path)

    def write_parquet(self, path: str) -> str:
        from ray_tpu.data.datasources import write_parquet
        return write_parquet(self, path)

    def schema(self) -> Optional[Dict[str, str]]:
        """Field -> type-name mapping sampled from the first non-empty
        block (reference: Dataset.schema()). Scalar-row datasets
        report {"value": <type>}; None when the dataset is empty."""
        ds = self.materialize()
        for ref in ds._block_refs:
            block = ray_tpu.get(_truncate_block.remote(ref, 1))
            if not block:
                continue
            row = block[0]
            if isinstance(row, dict):
                return {k: type(v).__name__ for k, v in row.items()}
            return {"value": type(row).__name__}
        return None

    def write_json(self, path: str) -> str:
        from ray_tpu.data.datasources import write_json
        return write_json(self, path)

    def write_numpy(self, path: str, column: str = "data") -> str:
        from ray_tpu.data.datasources import write_numpy
        return write_numpy(self, path, column)

    def union(self, other: "Dataset") -> "Dataset":
        a, b = self.materialize(), other.materialize()
        return Dataset(a._block_refs + b._block_refs)

    # --- consumption ------------------------------------------------------

    def iter_rows(self) -> Iterator[Any]:
        ds = self.materialize()
        for ref in ds._block_refs:
            yield from ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "default",
                     drop_last: bool = False) -> Iterator[BatchFormat]:
        buf: Block = []
        for row in self.iter_rows():
            buf.append(row)
            if len(buf) == batch_size:
                yield _to_batch(buf, batch_format)
                buf = []
        if buf and not drop_last:
            yield _to_batch(buf, batch_format)

    def iter_device_batches(self, mesh, *, batch_size: int,
                            drop_last: bool = True) -> Iterator[Any]:
        """Batches as mesh-sharded jax arrays (batch over data axes)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharding = NamedSharding(mesh, P(("dcn", "data", "fsdp")))
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if isinstance(batch, dict):
                yield {k: jax.device_put(v, sharding)
                       for k, v in batch.items()}
            else:
                yield jax.device_put(np.asarray(batch), sharding)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False,
                           dtypes=None) -> Iterator[Any]:
        """Batches as torch tensors (reference:
        Dataset.iter_torch_batches) — dict rows become dicts of
        tensors; scalar rows one tensor. ``dtypes`` optionally maps
        column -> torch dtype."""
        import torch

        def to_t(v, key=None):
            t = torch.as_tensor(np.asarray(v))
            if dtypes and key in dtypes:
                t = t.to(dtypes[key])
            return t

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if isinstance(batch, dict):
                yield {k: to_t(v, k) for k, v in batch.items()}
            elif batch and isinstance(batch[0], (tuple, list)):
                # tuple rows (e.g. from_torch (features, label)):
                # stack each position into its own tensor
                cols = list(zip(*batch))
                yield tuple(to_t(np.stack([np.asarray(x)
                                           for x in col]))
                            for col in cols)
            else:
                yield to_t(batch)

    def to_numpy(self, key: Optional[str] = None) -> np.ndarray:
        """Per-block remote conversion, concatenated on the driver (the
        result is a driver-resident ndarray by definition)."""
        ds = self.materialize()
        parts = [p for p in ray_tpu.get([_block_np.remote(b, key)
                                         for b in ds._block_refs])
                 if len(p)]
        if not parts:
            return np.asarray([])
        return np.concatenate(parts, axis=0)

    def __repr__(self):
        return (f"Dataset(num_blocks={self.num_blocks()}, "
                f"pending_stages={len(self._stages)})")


class GroupedDataset:
    """Hash-partitioned groupby (reference: data/grouped_dataset.py via
    _internal/push_based_shuffle.py): map tasks hash-partition each
    block by key, one reduce task per partition groups its rows and
    applies the aggregation. Rows never pass through the driver; the
    aggregated result is sorted by key with the distributed sort."""

    def __init__(self, ds: Dataset, key: Union[str, Callable]):
        self._ds = ds
        self._key = key

    def aggregate(self, agg_fn: Callable[[Any, List[Any]], Any]
                  ) -> Dataset:
        """Aggregated rows come back globally sorted by group key (any
        row type: the shuffle carries the key alongside each row, and
        the sort key is type-tagged so even mixed-type keys order
        deterministically instead of raising inside remote tasks)."""
        ds = self._ds.materialize()
        n_out = max(1, len(ds._block_refs))
        all_parts = _fan_out(_hash_partition, n_out, ds._block_refs,
                             shared_args=(self._key, n_out))
        agg_blocks = [_group_and_agg.remote(
                          self._key, agg_fn,
                          *[parts[j] for parts in all_parts])
                      for j in range(n_out)]
        keyed = Dataset(agg_blocks).sort(_gkey_sortable)
        return Dataset([_strip_gkey.remote(b)
                        for b in keyed._block_refs])

    def map_groups(self, fn: Callable[[List[Any]], Any]) -> Dataset:
        """Apply `fn` to each group's FULL row list (reference:
        grouped_dataset.map_groups): fn(rows) -> one row, or a LIST of
        rows which flattens into multiple output rows. Groups execute
        as the aggregate's reduce tasks; results come back ordered by
        group key."""
        _marker = "__raytpu_rowlist"

        def agg(_k, rows):
            out = fn(rows)
            if isinstance(out, list):
                return {_marker: out}
            return out

        ds = self.aggregate(agg)
        return ds.flat_map(
            lambda r: r[_marker]
            if isinstance(r, dict) and _marker in r else [r])

    def count(self) -> Dataset:
        return self.aggregate(
            lambda k, rows: {"key": k, "count": len(rows)})

    def sum(self, value_key: Union[str, Callable]) -> Dataset:
        getter = value_key if callable(value_key) else \
            (lambda r: r[value_key])
        return self.aggregate(
            lambda k, rows: {"key": k,
                             "sum": sum(getter(r) for r in rows)})


# --------------------------------------------------------------------------
# Datasources
# --------------------------------------------------------------------------

def from_items(items: List[Any], parallelism: int = 8) -> Dataset:
    items = list(items)
    n = max(1, min(parallelism, len(items) or 1))
    splits = np.array_split(np.arange(len(items)), n)
    return Dataset([ray_tpu.put([items[i] for i in idx])
                    for idx in splits])


def range_dataset(n: int, parallelism: int = 8) -> Dataset:
    return from_items(list(range(n)), parallelism)


def from_numpy(arr: np.ndarray, parallelism: int = 8) -> Dataset:
    chunks = np.array_split(arr, max(1, parallelism))
    return Dataset([ray_tpu.put([{"data": row} for row in chunk])
                    for chunk in chunks])


def read_csv(path: str, parallelism: int = 8) -> Dataset:
    """CSV rows as dicts (header required), one read task per file.
    Values parsed as int/float when possible."""
    from ray_tpu.data.datasources import _read_source
    return _read_source(path, "csv", parallelism)


def read_json(path: str, parallelism: int = 8) -> Dataset:
    """JSON-lines files, one read task per file."""
    from ray_tpu.data.datasources import _read_source
    return _read_source(path, "jsonl", parallelism)
