"""Distributed datasets: blocks in the object store, lazy stage plans.

Capability parity with the reference's Dataset core
(python/ray/data/dataset.py:124, blocks _internal/{plan.py,compute.py},
shuffle _internal/push_based_shuffle.py, datasources datasource/*): data
lives as blocks behind ObjectRefs; transforms are lazy stages fused into one
task per block at execution; map_batches supports task- or actor-pool
compute; shuffle/groupby are two-stage all-to-all jobs of remote tasks.

TPU-native addition: ``iter_device_batches(mesh)`` materializes batches
directly as mesh-sharded jax Arrays (the Train ingest path), and
``split(n)`` produces per-worker shards for SPMD gangs.
"""
from __future__ import annotations

import itertools
from typing import (Any, Callable, Dict, Iterator, List, Optional, Tuple,
                    Union)

import numpy as np

import ray_tpu

Block = List[Any]          # a block is a list of rows (or dict-batches)
BatchFormat = Union[List[Any], Dict[str, np.ndarray]]


# --------------------------------------------------------------------------
# Remote block workers
# --------------------------------------------------------------------------

@ray_tpu.remote(num_cpus=0.5)
def _apply_stages(block: Block, stages: Tuple) -> Block:
    for kind, fn in stages:
        if kind == "map":
            block = [fn(row) for row in block]
        elif kind == "filter":
            block = [row for row in block if fn(row)]
        elif kind == "flat_map":
            block = [out for row in block for out in fn(row)]
        elif kind == "map_batches":
            block = _apply_map_batches(block, fn)
    return block


def _apply_map_batches(block: Block, spec) -> Block:
    fn, batch_size, batch_format = spec
    out: Block = []
    for i in range(0, len(block), batch_size or len(block) or 1):
        chunk = block[i:i + batch_size] if batch_size else block
        batch = _to_batch(chunk, batch_format)
        res = fn(batch)
        out.extend(_from_batch(res))
        if not batch_size:
            break
    return out


def _to_batch(rows: Block, batch_format: str) -> BatchFormat:
    if batch_format == "numpy" and rows and isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return list(rows)


def _from_batch(batch: BatchFormat) -> Block:
    if isinstance(batch, dict):
        keys = list(batch.keys())
        n = len(batch[keys[0]])
        return [{k: batch[k][i] for k in keys} for i in range(n)]
    return list(batch)


def _key_getter(key):
    if key is None:
        return lambda r: r
    return key if callable(key) else (lambda r: r[key])


class _BatchActor:
    """Actor-pool compute for map_batches (reference:
    _internal/compute.py ActorPoolStrategy)."""

    def __init__(self, fn_constructor: Optional[Callable] = None):
        self.fn = fn_constructor() if fn_constructor else None

    def apply(self, block: Block, stages: Tuple) -> Block:
        for kind, spec in stages:
            if kind == "map_batches_actor":
                fn, batch_size, batch_format = spec
                target = self.fn if self.fn is not None else fn
                block = _apply_map_batches(
                    block, (target, batch_size, batch_format))
        return block


# --------------------------------------------------------------------------
# Dataset
# --------------------------------------------------------------------------

class Dataset:
    def __init__(self, block_refs: List[ray_tpu.ObjectRef],
                 stages: Tuple = ()):
        from ray_tpu._private.usage_stats import record_library_usage
        record_library_usage("data")
        self._block_refs = list(block_refs)
        self._stages = tuple(stages)

    # --- lazy transforms --------------------------------------------------

    def _with_stage(self, stage) -> "Dataset":
        return Dataset(self._block_refs, self._stages + (stage,))

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._with_stage(("map", fn))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._with_stage(("filter", fn))

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        return self._with_stage(("flat_map", fn))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = 256,
                    batch_format: str = "default",
                    compute: str = "tasks",
                    num_actors: int = 2,
                    fn_constructor: Optional[Callable] = None
                    ) -> "Dataset":
        if compute == "tasks":
            return self._with_stage(
                ("map_batches", (fn, batch_size, batch_format)))
        # Actor-pool compute executes eagerly over materialized blocks.
        ds = self.materialize()
        actor_cls = ray_tpu.remote(_BatchActor)
        actors = [actor_cls.remote(fn_constructor)
                  for _ in range(num_actors)]
        stage = (("map_batches_actor", (fn, batch_size, batch_format)),)
        refs = []
        for i, block_ref in enumerate(ds._block_refs):
            actor = actors[i % num_actors]
            refs.append(actor.apply.remote(block_ref, stage))
        blocks = ray_tpu.get(refs)
        for a in actors:
            ray_tpu.kill(a)
        return Dataset([ray_tpu.put(b) for b in blocks])

    # --- execution --------------------------------------------------------

    def materialize(self) -> "Dataset":
        if not self._stages:
            return self
        refs = [_apply_stages.remote(b, self._stages)
                for b in self._block_refs]
        # Resolve now so errors surface here.
        blocks = ray_tpu.get(refs)
        return Dataset([ray_tpu.put(b) for b in blocks])

    def _resolved_blocks(self) -> List[Block]:
        ds = self.materialize()
        return ray_tpu.get(list(ds._block_refs))

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        ds = self.materialize()
        for ref in ds._block_refs:
            out.extend(ray_tpu.get(ref))
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Any]:
        return [row for b in self._resolved_blocks() for row in b]

    def count(self) -> int:
        ds = self.materialize()

        @ray_tpu.remote(num_cpus=0.25)
        def _len(b):
            return len(b)
        return sum(ray_tpu.get([_len.remote(r)
                                for r in ds._block_refs]))

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def sum(self, key: Optional[Union[str, Callable]] = None):
        rows = self.take_all()
        if key is None:
            return sum(rows)
        getter = key if callable(key) else (lambda r: r[key])
        return sum(getter(r) for r in rows)

    def mean(self, key: Optional[Union[str, Callable]] = None):
        n = self.count()
        return self.sum(key) / n if n else float("nan")

    # --- reorganization ---------------------------------------------------

    def repartition(self, num_blocks: int) -> "Dataset":
        rows = self.take_all()
        splits = np.array_split(np.arange(len(rows)), num_blocks)
        blocks = [[rows[i] for i in idx] for idx in splits]
        return Dataset([ray_tpu.put(b) for b in blocks])

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Two-stage all-to-all shuffle (reference:
        _internal/push_based_shuffle.py shape): stage 1 splits each block
        into N random parts; stage 2 merges part i of every block."""
        ds = self.materialize()
        n = max(1, len(ds._block_refs))

        @ray_tpu.remote(num_cpus=0.25, num_returns=n)
        def split_block(block, seed_i):
            rng = np.random.RandomState(seed_i)
            perm = rng.permutation(len(block))
            parts = np.array_split(perm, n)
            out = [[block[i] for i in part] for part in parts]
            return out if n > 1 else out[0]

        @ray_tpu.remote(num_cpus=0.25)
        def merge(seed_i, *parts):
            merged = [row for p in parts for row in p]
            rng = np.random.RandomState(seed_i + 10000)
            perm = rng.permutation(len(merged))
            return [merged[i] for i in perm]

        base = seed if seed is not None else 0
        all_parts = [split_block.remote(b, base + i)
                     for i, b in enumerate(ds._block_refs)]
        if n == 1:
            all_parts = [[p] for p in all_parts]
        merged = [merge.remote(base + j,
                               *[parts[j] for parts in all_parts])
                  for j in range(n)]
        return Dataset(merged)

    def sort(self, key: Optional[Union[str, Callable]] = None,
             descending: bool = False) -> "Dataset":
        rows = self.take_all()
        getter = (key if callable(key)
                  else (lambda r: r[key]) if key else (lambda r: r))
        rows.sort(key=getter, reverse=descending)
        n = max(1, self.num_blocks())
        splits = np.array_split(np.arange(len(rows)), n)
        return Dataset([ray_tpu.put([rows[i] for i in idx])
                        for idx in splits])

    def groupby(self, key: Union[str, Callable]) -> "GroupedDataset":
        return GroupedDataset(self, key)

    def split(self, n: int) -> List["Dataset"]:
        """Per-worker shards (equal row counts ±1)."""
        rows = self.take_all()
        splits = np.array_split(np.arange(len(rows)), n)
        return [Dataset([ray_tpu.put([rows[i] for i in idx])])
                for idx in splits]

    def window(self, *, blocks_per_window: int = 2):
        """Streaming windows (reference: Dataset.window ->
        DatasetPipeline)."""
        import builtins
        from ray_tpu.data.pipeline import DatasetPipeline
        blocks = self._block_refs
        stages = self._stages
        windows = [Dataset(blocks[i:i + blocks_per_window], stages)
                   for i in builtins.range(0, len(blocks),
                                           blocks_per_window)]
        return DatasetPipeline.from_windows(windows)

    def repeat(self, times: Optional[int] = None):
        """Epoch repetition (reference: Dataset.repeat)."""
        return self.window(
            blocks_per_window=max(1, len(self._block_refs))
        ).repeat(times)

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-wise zip (reference: Dataset.zip)."""
        a = self.take_all()
        b = other.take_all()
        if len(a) != len(b):
            raise ValueError(
                f"zip() requires equal lengths, got {len(a)} vs {len(b)}")
        import builtins
        rows = []
        for x, y in builtins.zip(a, b):
            if isinstance(x, dict) and isinstance(y, dict):
                merged = dict(x)
                for k, v in y.items():
                    merged[k if k not in merged else f"{k}_1"] = v
                rows.append(merged)
            else:
                rows.append((x, y))
        from ray_tpu.data.dataset import from_items
        return from_items(rows, max(1, self.num_blocks()))

    def limit(self, n: int) -> "Dataset":
        from ray_tpu.data.dataset import from_items
        return from_items(self.take(n), max(1, self.num_blocks()))

    def unique(self, key: Optional[Union[str, Callable]] = None
               ) -> List[Any]:
        getter = _key_getter(key)
        seen = []
        seen_set = set()
        for row in self.iter_rows():
            v = getter(row)
            if v not in seen_set:
                seen_set.add(v)
                seen.append(v)
        return seen

    def min(self, key: Optional[Union[str, Callable]] = None):
        import builtins
        getter = _key_getter(key)
        return builtins.min(getter(r) for r in self.iter_rows())

    def max(self, key: Optional[Union[str, Callable]] = None):
        import builtins
        getter = _key_getter(key)
        return builtins.max(getter(r) for r in self.iter_rows())

    def to_pandas(self):
        from ray_tpu.data.datasources import to_pandas
        return to_pandas(self)

    def write_csv(self, path: str) -> str:
        from ray_tpu.data.datasources import write_csv
        return write_csv(self, path)

    def write_json(self, path: str) -> str:
        from ray_tpu.data.datasources import write_json
        return write_json(self, path)

    def write_numpy(self, path: str, column: str = "data") -> str:
        from ray_tpu.data.datasources import write_numpy
        return write_numpy(self, path, column)

    def union(self, other: "Dataset") -> "Dataset":
        a, b = self.materialize(), other.materialize()
        return Dataset(a._block_refs + b._block_refs)

    # --- consumption ------------------------------------------------------

    def iter_rows(self) -> Iterator[Any]:
        ds = self.materialize()
        for ref in ds._block_refs:
            yield from ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "default",
                     drop_last: bool = False) -> Iterator[BatchFormat]:
        buf: Block = []
        for row in self.iter_rows():
            buf.append(row)
            if len(buf) == batch_size:
                yield _to_batch(buf, batch_format)
                buf = []
        if buf and not drop_last:
            yield _to_batch(buf, batch_format)

    def iter_device_batches(self, mesh, *, batch_size: int,
                            drop_last: bool = True) -> Iterator[Any]:
        """Batches as mesh-sharded jax arrays (batch over data axes)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharding = NamedSharding(mesh, P(("dcn", "data", "fsdp")))
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if isinstance(batch, dict):
                yield {k: jax.device_put(v, sharding)
                       for k, v in batch.items()}
            else:
                yield jax.device_put(np.asarray(batch), sharding)

    def to_numpy(self, key: Optional[str] = None) -> np.ndarray:
        rows = self.take_all()
        if key is not None:
            return np.asarray([r[key] for r in rows])
        return np.asarray(rows)

    def __repr__(self):
        return (f"Dataset(num_blocks={self.num_blocks()}, "
                f"pending_stages={len(self._stages)})")


class GroupedDataset:
    """Hash-partitioned groupby (reference: data/grouped_dataset.py)."""

    def __init__(self, ds: Dataset, key: Union[str, Callable]):
        self._ds = ds
        self._key = key if callable(key) else (lambda r, k=key: r[k])

    def _groups(self) -> Dict[Any, List[Any]]:
        groups: Dict[Any, List[Any]] = {}
        for row in self._ds.iter_rows():
            groups.setdefault(self._key(row), []).append(row)
        return groups

    def count(self) -> Dataset:
        items = [{"key": k, "count": len(v)}
                 for k, v in sorted(self._groups().items())]
        return from_items(items)

    def aggregate(self, agg_fn: Callable[[Any, List[Any]], Any]
                  ) -> Dataset:
        items = [agg_fn(k, v) for k, v in sorted(self._groups().items())]
        return from_items(items)

    def sum(self, value_key: Union[str, Callable]) -> Dataset:
        getter = value_key if callable(value_key) else \
            (lambda r: r[value_key])
        return self.aggregate(
            lambda k, rows: {"key": k,
                             "sum": sum(getter(r) for r in rows)})


# --------------------------------------------------------------------------
# Datasources
# --------------------------------------------------------------------------

def from_items(items: List[Any], parallelism: int = 8) -> Dataset:
    items = list(items)
    n = max(1, min(parallelism, len(items) or 1))
    splits = np.array_split(np.arange(len(items)), n)
    return Dataset([ray_tpu.put([items[i] for i in idx])
                    for idx in splits])


def range_dataset(n: int, parallelism: int = 8) -> Dataset:
    return from_items(list(range(n)), parallelism)


def from_numpy(arr: np.ndarray, parallelism: int = 8) -> Dataset:
    chunks = np.array_split(arr, max(1, parallelism))
    return Dataset([ray_tpu.put([{"data": row} for row in chunk])
                    for chunk in chunks])


def read_csv(path: str, parallelism: int = 8) -> Dataset:
    """CSV rows as dicts (header required). Values parsed as float when
    possible."""
    import csv
    import glob as globlib
    rows: List[Dict[str, Any]] = []
    paths = sorted(globlib.glob(path)) or [path]
    for p in paths:
        with open(p, newline="") as f:
            for row in csv.DictReader(f):
                parsed = {}
                for k, v in row.items():
                    try:
                        parsed[k] = float(v) if "." in v or "e" in v \
                            else int(v)
                    except (ValueError, TypeError):
                        parsed[k] = v
                rows.append(parsed)
    return from_items(rows, parallelism)


def read_json(path: str, parallelism: int = 8) -> Dataset:
    """JSON-lines files."""
    import glob as globlib
    import json
    rows = []
    paths = sorted(globlib.glob(path)) or [path]
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
    return from_items(rows, parallelism)
