"""Random-access (key -> row) serving over a sorted Dataset.

Capability parity with the reference's RandomAccessDataset
(python/ray/data/random_access_dataset.py: sort by key, pin the
sorted blocks in a pool of actors, route each lookup to the actor
holding the covering block via binary search over block boundaries).
Same shape here: the dataset is sample-sorted once, each accessor
actor pins a contiguous slice of the sorted blocks in memory, and
the driver-side handle binary-searches per-block key ranges to route
gets; multiget batches per actor so a fan-out of keys costs one
actor call per touched actor, not one per key.
"""
from __future__ import annotations

import bisect
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu


def _key_fn(key: Union[str, Callable]) -> Callable[[Any], Any]:
    if callable(key):
        return key
    return lambda row: row[key]


class _RandomAccessWorker:
    """Pins sorted blocks in process memory; serves bisect lookups."""

    def __init__(self, key: Union[str, Callable]):
        self._key = _key_fn(key)
        self._blocks: Dict[int, List[Any]] = {}
        self._keys: Dict[int, List[Any]] = {}
        self.num_gets = 0

    def load(self, block_idx: int, block: List[Any]):
        """Pin a block; returns (row_count, first_key) so the build
        needs no second pass over the rows for routing bounds."""
        rows = list(block)
        self._blocks[block_idx] = rows
        keys = [self._key(r) for r in rows]
        self._keys[block_idx] = keys
        return len(rows), (keys[0] if keys else None)

    def get(self, block_idx: int, k: Any) -> Optional[Any]:
        self.num_gets += 1
        keys = self._keys.get(block_idx)
        if not keys:
            return None
        i = bisect.bisect_left(keys, k)
        if i < len(keys) and keys[i] == k:
            return self._blocks[block_idx][i]
        return None

    def multiget(self, block_idxs: List[int],
                 ks: List[Any]) -> List[Optional[Any]]:
        return [self.get(b, k) for b, k in zip(block_idxs, ks)]

    def stats(self) -> Dict[str, Any]:
        return {"num_blocks": len(self._blocks),
                "num_rows": sum(len(b) for b in self._blocks.values()),
                "num_gets": self.num_gets}


class RandomAccessDataset:
    """Handle returned by Dataset.to_random_access() (also directly
    constructible from an unsorted dataset, which it sorts first)."""

    def __init__(self, ds, key: Union[str, Callable],
                 num_workers: int = 2, _sorted: bool = False):
        sorted_ds = ds if _sorted else ds.sort(key).materialize()
        blocks = sorted_ds._block_refs
        worker_cls = ray_tpu.remote(num_cpus=0.25)(_RandomAccessWorker)
        self._workers = [worker_cls.remote(key)
                         for _ in range(max(1, num_workers))]
        # Contiguous block slices per worker keep each actor's pinned
        # range compact (one actor per lookup, like the reference's
        # block->actor assignment).
        self._owner: List[int] = []
        loads = []
        for i, ref in enumerate(blocks):
            w = min(i * len(self._workers) // max(1, len(blocks)),
                    len(self._workers) - 1)
            self._owner.append(w)
            loads.append(self._workers[w].load.remote(i, ref))
        loaded = ray_tpu.get(loads)
        # Routing mins come straight from the load pass (blocks are
        # already sorted, so each block's first key is its lower bound).
        self._mins: List[Any] = []
        self._blocks_with_rows: List[int] = []
        for i, (n, first) in enumerate(loaded):
            if n:
                self._blocks_with_rows.append(i)
                self._mins.append(first)
        self._num_rows = sum(n for n, _ in loaded)

    def _route(self, k: Any) -> List[int]:
        """Candidate block indices for key k: the covering block, plus
        the next one (duplicate runs of k may spill over a boundary
        whose min equals k)."""
        if not self._mins:
            return []
        j = bisect.bisect_right(self._mins, k) - 1
        out = []
        if j >= 0:
            out.append(self._blocks_with_rows[j])
        if j + 1 < len(self._mins) and self._mins[j + 1] == k:
            out.append(self._blocks_with_rows[j + 1])
        return out

    def get(self, k: Any) -> Optional[Any]:
        """Blocking point lookup."""
        return ray_tpu.get(self.get_async(k))

    def get_async(self, k: Any):
        """ObjectRef to the row with key k (None if absent)."""
        cands = self._route(k)
        if not cands:
            return ray_tpu.put(None)
        b = cands[0]
        ref = self._workers[self._owner[b]].get.remote(b, k)
        if len(cands) == 1:
            return ref
        return _first_hit.remote(
            ref, self._workers[self._owner[cands[1]]].get.remote(
                cands[1], k))

    def multiget(self, ks: List[Any]) -> List[Optional[Any]]:
        """Batched lookup: one actor call per touched actor."""
        per_worker: Dict[int, List[int]] = {}
        routed: List[Optional[tuple]] = []
        for i, k in enumerate(ks):
            cands = self._route(k)
            if not cands:
                routed.append(None)
                continue
            w = self._owner[cands[0]]
            per_worker.setdefault(w, [])
            per_worker[w].append(i)
            routed.append((w, cands))
        calls = {}
        for w, idxs in per_worker.items():
            calls[w] = self._workers[w].multiget.remote(
                [routed[i][1][0] for i in idxs],
                [ks[i] for i in idxs])
        results: List[Optional[Any]] = [None] * len(ks)
        for w, idxs in per_worker.items():
            vals = ray_tpu.get(calls[w])
            for i, v in zip(idxs, vals):
                results[i] = v
        # Boundary-straddling duplicates: retry misses on the spillover
        # block (rare; one extra call per miss).
        for i, k in enumerate(ks):
            if results[i] is None and routed[i] is not None and \
                    len(routed[i][1]) > 1:
                b = routed[i][1][1]
                results[i] = ray_tpu.get(
                    self._workers[self._owner[b]].get.remote(b, k))
        return results

    def stats(self) -> str:
        per = ray_tpu.get([w.stats.remote() for w in self._workers])
        lines = [f"RandomAccessDataset: {self._num_rows} rows, "
                 f"{len(self._owner)} blocks, {len(per)} workers"]
        for i, s in enumerate(per):
            lines.append(f"  worker {i}: {s['num_rows']} rows in "
                         f"{s['num_blocks']} blocks, "
                         f"{s['num_gets']} gets")
        return "\n".join(lines)


@ray_tpu.remote(num_cpus=0.25)
def _first_hit(a, b):
    return a if a is not None else b
