"""Distributed datasets (reference: python/ray/data — SURVEY.md §2.3 L5).

Blocks live in the object store; transforms are lazy fused stages;
pipelines stream windows; datasources cover csv/json/text/binary/numpy
(+ gated parquet); actor-pool compute for stateful batch inference;
``iter_device_batches`` feeds sharded jax arrays onto a device mesh.
"""
from ray_tpu.data.dataset import (Dataset, from_items, from_numpy,
                                  range_dataset, read_csv, read_json)
from ray_tpu.data.datasources import (RandomAccessDataset,
                                      from_huggingface, from_pandas,
                                      from_torch, read_binary_files,
                                      read_numpy, read_parquet,
                                      read_text, to_pandas, write_csv,
                                      write_json, write_numpy,
                                      write_parquet)
from ray_tpu.data.pipeline import DatasetPipeline


def range(n: int, parallelism: int = 8) -> Dataset:  # noqa: A001
    """ray_tpu.data.range(n) — mirrors the reference's ray.data.range."""
    return range_dataset(n, parallelism)


__all__ = [
    "Dataset", "DatasetPipeline", "RandomAccessDataset",
    "from_items", "from_numpy", "from_pandas", "from_torch",
    "from_huggingface", "range", "range_dataset",
    "read_csv", "read_json", "read_text", "read_binary_files",
    "read_numpy", "read_parquet", "to_pandas",
    "write_csv", "write_json", "write_numpy", "write_parquet",
]
