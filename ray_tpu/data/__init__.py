from ray_tpu.data.dataset import (Dataset, from_items, from_numpy,
                                  range_dataset, read_csv, read_json)


def range(n: int, parallelism: int = 8) -> Dataset:  # noqa: A001
    """ray_tpu.data.range(n) — mirrors the reference's ray.data.range."""
    return range_dataset(n, parallelism)


__all__ = ["Dataset", "from_items", "from_numpy", "range",
           "range_dataset", "read_csv", "read_json"]
