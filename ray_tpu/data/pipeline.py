"""DatasetPipeline: windowed/repeated streaming execution.

Capability parity with the reference's DatasetPipeline
(python/ray/data/dataset_pipeline.py — ``ds.window(blocks_per_window)``
/ ``ds.repeat(n)`` produce a pipeline whose windows execute their lazy
stages one window at a time, bounding memory; per-epoch iteration via
``iter_epochs``). TPU-relevant: ``iter_device_batches`` feeds a mesh one
window at a time so host RAM holds only a window of blocks.
"""
from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional

import ray_tpu


class DatasetPipeline:
    def __init__(self, windows_fn: Callable[[], Iterator["Dataset"]],
                 length: Optional[int] = None,
                 epoch_fn: Optional[
                     Callable[[], Iterator["Dataset"]]] = None):
        self._windows_fn = windows_fn
        self._length = length
        # One epoch's windows (set by repeat(); used by iter_epochs so
        # an "epoch" is one pass over the base data, not the whole
        # repeated stream).
        self._epoch_fn = epoch_fn or windows_fn

    # --- construction helpers (used by Dataset.window/repeat) -------------

    @classmethod
    def from_windows(cls, datasets: List["Dataset"]) -> "DatasetPipeline":
        return cls(lambda: iter(datasets), length=len(datasets))

    # --- transforms (applied lazily per window) ---------------------------

    def map(self, fn, *,
            target_max_block_size: Optional[int] = None
            ) -> "DatasetPipeline":
        base = self._windows_fn
        split = self._splitter(target_max_block_size)
        return DatasetPipeline(
            lambda: (split(w.map(fn)) for w in base()), self._length)

    def map_batches(self, fn,
                    target_max_block_size: Optional[int] = None,
                    **kwargs) -> "DatasetPipeline":
        """Per-window ``Dataset.map_batches``. With
        ``target_max_block_size`` set, every window's output blocks
        are re-split under that row cap at the map boundary
        (``Dataset.split_oversized_blocks``): a skewed source block
        — or a flat_map-style expansion inside ``fn`` — can't emerge
        as one giant block that a downstream consumer (the batch
        tier's prefill window, a device batch) must swallow whole."""
        base = self._windows_fn
        split = self._splitter(target_max_block_size)
        return DatasetPipeline(
            lambda: (split(w.map_batches(fn, **kwargs))
                     for w in base()),
            self._length)

    def filter(self, fn, *,
               target_max_block_size: Optional[int] = None
               ) -> "DatasetPipeline":
        base = self._windows_fn
        split = self._splitter(target_max_block_size)
        return DatasetPipeline(
            lambda: (split(w.filter(fn)) for w in base()),
            self._length)

    @staticmethod
    def _splitter(target_max_block_size: Optional[int]):
        """Identity when no cap is set; otherwise the map-boundary
        block-size guard (splitting materializes the window's pending
        stages — windows execute eagerly on consumption anyway, so
        the barrier stays window-local). Stats collection rides along
        so a consumer that reads ``stats_dict()`` per window (the
        batch tier's progress manifests) still gets the per-stage
        report the split's materialization would otherwise swallow;
        cost is one extra ObjectRef per block, only when a cap is
        set."""
        if target_max_block_size is None:
            return lambda w: w
        return lambda w: w.split_oversized_blocks(
            target_max_block_size, collect_stats=True)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        base = self._windows_fn

        def gen():
            epoch = 0
            while times is None or epoch < times:
                yield from base()
                epoch += 1

        return DatasetPipeline(
            gen, None if times is None or self._length is None
            else self._length * times,
            epoch_fn=base)

    # --- consumption ------------------------------------------------------

    def iter_windows(self) -> Iterator["Dataset"]:
        return self._windows_fn()

    def iter_rows(self) -> Iterator[Any]:
        for w in self.iter_windows():
            yield from w.iter_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "default") -> Iterator[Any]:
        for w in self.iter_windows():
            yield from w.iter_batches(batch_size=batch_size,
                                      batch_format=batch_format)

    def iter_epochs(self, num_epochs: int) -> Iterator["DatasetPipeline"]:
        """Yields a one-epoch pipeline per epoch (for a repeat()ed
        pipeline, one pass over the BASE data each — reference:
        DatasetPipeline.iter_epochs)."""
        for _ in range(num_epochs):
            yield DatasetPipeline(self._epoch_fn)

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        return sum(w.count() for w in self.iter_windows())

    def num_windows(self) -> Optional[int]:
        return self._length

    def split(self, n: int) -> List["DatasetPipeline"]:
        """Lazy round-robin window split for n consumers (reference:
        pipeline.split for per-worker shards). Works on unbounded
        repeat() pipelines: each shard re-walks the window generator
        and takes every n-th window."""
        import itertools
        base = self._windows_fn
        length = None if self._length is None else \
            (self._length + n - 1) // n

        def shard_fn(i):
            return lambda: itertools.islice(base(), i, None, n)

        return [DatasetPipeline(shard_fn(i), length)
                for i in range(n)]

    def __repr__(self):
        w = "?" if self._length is None else self._length
        return f"DatasetPipeline(num_windows={w})"


from ray_tpu.data.dataset import Dataset  # noqa: E402  (cycle-free tail)
