"""``multiprocessing.Pool`` API backed by actors.

Capability parity with the reference's drop-in pool
(python/ray/util/multiprocessing/pool.py): ``Pool`` exposes
apply/apply_async/map/map_async/starmap/imap/imap_unordered/close/join/
terminate with the stdlib's semantics, but each "process" is an actor, so
the pool composes with the cluster scheduler and with TPU resource requests
(``ray_remote_args={"num_tpus": 1}``).
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional, Tuple

import ray_tpu

__all__ = ["Pool", "AsyncResult", "TimeoutError"]

TimeoutError = ray_tpu.exceptions.GetTimeoutError


@ray_tpu.remote
class _PoolActor:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_batch(self, func, argument_tuples: List[Tuple[tuple, dict]]):
        return [func(*a, **kw) for a, kw in argument_tuples]

    def ping(self):
        return True


class AsyncResult:
    """Stdlib-compatible handle over a set of chunk refs."""

    def __init__(self, chunk_refs: List[Any], single: bool = False,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._chunk_refs = chunk_refs
        self._single = single
        self._result = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._collect, args=(callback, error_callback),
            daemon=True)
        self._thread.start()

    def _collect(self, callback, error_callback):
        try:
            chunks = ray_tpu.get(self._chunk_refs)
            flat = list(itertools.chain.from_iterable(chunks))
            self._result = flat[0] if self._single else flat
            self._done.set()
            if callback is not None:
                callback(self._result)
        except BaseException as e:  # noqa: BLE001 — stored and re-raised
            self._error = e
            self._done.set()
            if error_callback is not None:
                error_callback(e)

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._result

    def wait(self, timeout: Optional[float] = None):
        self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        return self._error is None


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (),
                 ray_remote_args: Optional[dict] = None):
        if processes is None:
            processes = max(1, int(
                ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._size = processes
        cls = _PoolActor
        if ray_remote_args:
            cls = cls.options(**ray_remote_args)
        self._actors = [cls.remote(initializer, initargs)
                        for _ in range(processes)]
        ray_tpu.get([a.ping.remote() for a in self._actors])
        self._rr = itertools.cycle(range(processes))
        self._closed = False
        self._outstanding: List[AsyncResult] = []

    # -- helpers -----------------------------------------------------------

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")

    def _submit_chunks(self, func, arg_tuples: List[Tuple[tuple, dict]],
                       chunksize: Optional[int]):
        if chunksize is None:
            chunksize = max(1, len(arg_tuples) // (self._size * 4) or 1)
        refs = []
        for i in range(0, len(arg_tuples), chunksize):
            actor = self._actors[next(self._rr)]
            refs.append(actor.run_batch.remote(
                func, arg_tuples[i:i + chunksize]))
        return refs

    # -- stdlib API --------------------------------------------------------

    def apply(self, func, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args=(), kwds=None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_open()
        refs = self._submit_chunks(func, [(tuple(args), kwds or {})], 1)
        r = AsyncResult(refs, single=True, callback=callback,
                        error_callback=error_callback)
        self._outstanding.append(r)
        return r

    def map(self, func, iterable: Iterable, chunksize=None) -> List[Any]:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable, chunksize=None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check_open()
        tuples = [((x,), {}) for x in iterable]
        refs = self._submit_chunks(func, tuples, chunksize)
        r = AsyncResult(refs, callback=callback,
                        error_callback=error_callback)
        self._outstanding.append(r)
        return r

    def starmap(self, func, iterable, chunksize=None) -> List[Any]:
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable, chunksize=None, callback=None,
                      error_callback=None) -> AsyncResult:
        self._check_open()
        tuples = [(tuple(args), {}) for args in iterable]
        refs = self._submit_chunks(func, tuples, chunksize)
        r = AsyncResult(refs, callback=callback,
                        error_callback=error_callback)
        self._outstanding.append(r)
        return r

    def imap(self, func, iterable, chunksize=1):
        self._check_open()
        tuples = [((x,), {}) for x in iterable]
        refs = self._submit_chunks(func, tuples, chunksize)
        for ref in refs:  # ordered
            yield from ray_tpu.get(ref)

    def imap_unordered(self, func, iterable, chunksize=1):
        self._check_open()
        tuples = [((x,), {}) for x in iterable]
        pending = set(self._submit_chunks(func, tuples, chunksize))
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1)
            pending.discard(ready[0])
            yield from ray_tpu.get(ready[0])

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            ray_tpu.kill(a)
        self._actors = []

    def join(self):
        """Block until all outstanding async work finishes (stdlib
        close()/join() completion-barrier contract)."""
        if not self._closed:
            raise ValueError("Pool is still open")
        for r in self._outstanding:
            r.wait()
        self._outstanding = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
