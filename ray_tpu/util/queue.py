"""Distributed Queue (reference: python/ray/util/queue.py): a FIFO queue
backed by an actor, usable from any task/actor/driver."""
from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None):
        try:
            await asyncio.wait_for(self.q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            return True, await asyncio.wait_for(self.q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    def qsize(self) -> int:
        return self.q.qsize()

    def empty(self) -> bool:
        return self.q.empty()

    def full(self) -> bool:
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0):
        actor_cls = ray_tpu.remote(_QueueActor)
        self.actor = actor_cls.options(num_cpus=0).remote(maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None):
        ok = ray_tpu.get(self.actor.put.remote(
            item, timeout if block else 0.001))
        if not ok:
            raise Full("queue full")

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        ok, item = ray_tpu.get(self.actor.get.remote(
            timeout if block else 0.001))
        if not ok:
            raise Empty("queue empty")
        return item

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self):
        ray_tpu.kill(self.actor)
