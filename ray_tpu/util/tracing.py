"""Distributed tracing: spans around remote calls with context
propagation.

Capability parity with the reference's tracing helper
(python/ray/util/tracing/tracing_helper.py:290,324,449 — span capture
around every ``.remote()`` invocation and task/actor execution, with the
trace context propagated into the callee so cross-process call chains
share one trace). OpenTelemetry isn't a baked-in dependency, so spans go
to a pluggable exporter (in-memory by default, JSON dump helper); an OTel
exporter can be plugged via ``setup_tracing(exporter=...)``.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

_state = threading.local()
_lock = threading.Lock()
_enabled = False
_spans: List[Dict[str, Any]] = []
_exporter: Optional[Callable[[Dict[str, Any]], None]] = None
_trace_dir: Optional[str] = None
_TRACE_DIR_ENV = "RAY_TPU_TRACE_DIR"


def setup_tracing(exporter: Optional[Callable[[Dict[str, Any]], None]]
                  = None, trace_dir: Optional[str] = None) -> None:
    """Enable tracing (reference: ray.init(_tracing_startup_hook=...)).

    ``trace_dir`` (default: a per-pid dir under /tmp/ray_tpu/traces) is
    exported via env so worker processes SPAWNED AFTER this call
    self-enable and append their spans as JSONL there; get_spans()
    merges them back. Workers already running keep tracing disabled.
    """
    global _enabled, _exporter, _trace_dir
    _enabled = True
    _exporter = exporter
    _trace_dir = trace_dir or os.path.join(
        "/tmp", "ray_tpu", "traces", f"driver-{os.getpid()}")
    os.makedirs(_trace_dir, exist_ok=True)
    os.environ[_TRACE_DIR_ENV] = _trace_dir


def _maybe_enable_from_env() -> bool:
    """Worker-process self-enable: a shipped trace context plus the
    inherited trace-dir env turns tracing on with a file sink."""
    global _enabled, _trace_dir
    if _enabled:
        return True
    env_dir = os.environ.get(_TRACE_DIR_ENV)
    if not env_dir:
        return False
    _trace_dir = env_dir
    _enabled = True
    return True


def teardown_tracing() -> None:
    global _enabled, _exporter, _trace_dir
    _enabled = False
    _exporter = None
    if _trace_dir is not None:
        import shutil
        shutil.rmtree(_trace_dir, ignore_errors=True)
    _trace_dir = None
    os.environ.pop(_TRACE_DIR_ENV, None)
    with _lock:
        _spans.clear()


def is_enabled() -> bool:
    return _enabled


def get_spans(include_workers: bool = True) -> List[Dict[str, Any]]:
    with _lock:
        out = list(_spans)
    if include_workers and _trace_dir and os.path.isdir(_trace_dir):
        own = f"{os.getpid()}.jsonl"   # own spans are already in _spans
        for fname in os.listdir(_trace_dir):
            if not fname.endswith(".jsonl") or fname == own:
                continue
            try:
                with open(os.path.join(_trace_dir, fname)) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            out.append(json.loads(line))
            except OSError:
                pass
    return out


def export_json(path: str) -> str:
    with _lock:
        data = list(_spans)
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
    return path


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def current_context() -> Optional[Dict[str, str]]:
    return getattr(_state, "ctx", None)


def _emit(span: Dict[str, Any]) -> None:
    with _lock:
        _spans.append(span)
    if _exporter is not None:
        try:
            _exporter(span)
        except Exception:
            pass
    if _trace_dir is not None:
        # Cross-process sink: every process appends to its own file.
        try:
            path = os.path.join(_trace_dir, f"{os.getpid()}.jsonl")
            with open(path, "a") as f:
                f.write(json.dumps(span) + "\n")
        except OSError:
            pass


class span:
    """Context manager recording one span; sets the thread-local context
    so nested remote calls become children."""

    def __init__(self, name: str, kind: str = "internal",
                 parent: Optional[Dict[str, str]] = None,
                 attributes: Optional[Dict[str, Any]] = None):
        self.name = name
        self.kind = kind
        self.attributes = dict(attributes or {})
        explicit_parent = parent if parent is not None \
            else current_context()
        self.trace_id = (explicit_parent or {}).get(
            "trace_id") or _new_id()
        self.parent_id = (explicit_parent or {}).get("span_id")
        self.span_id = _new_id()
        self._prev_ctx = None
        self._start = 0.0

    @property
    def context(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def __enter__(self) -> "span":
        self._start = time.time()
        self._prev_ctx = current_context()
        _state.ctx = self.context
        return self

    def __exit__(self, exc_type, exc, tb):
        _state.ctx = self._prev_ctx
        if not _enabled:
            return False
        _emit({
            "name": self.name,
            "kind": self.kind,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_time": self._start,
            "end_time": time.time(),
            "status": "error" if exc_type else "ok",
            "attributes": self.attributes,
        })
        return False


def invocation_context(task_name: str, kind: str
                       ) -> Optional[Dict[str, str]]:
    """Called by the API layer on ``.remote()``: records the client-side
    invocation span and returns the context to ship with the spec."""
    if not _enabled:
        return None
    with span(f"{task_name}.remote", kind=kind,
              attributes={"task": task_name}) as s:
        return s.context


def execution_span(task_name: str, kind: str,
                   ctx: Optional[Dict[str, str]]):
    """Called by executors around the user function: the server-side
    span, parented to the shipped invocation context."""
    if ctx is not None:
        _maybe_enable_from_env()
    if not _enabled:
        import contextlib
        return contextlib.nullcontext()
    return span(f"{task_name}.execute", kind=kind, parent=ctx,
                attributes={"task": task_name})
