"""Out-of-band collective groups over actors.

Capability parity with the reference's ray.util.collective
(python/ray/util/collective/collective.py — NCCL/gloo groups with a named
rendezvous store actor): allreduce/allgather/broadcast/reduce/barrier for
host (numpy) data between actor processes, rendezvoused through a named
group actor.

TPU-native note (SURVEY.md §5.8): DEVICE collectives are in-band to XLA —
psum/all_gather/ppermute over mesh axes inside pjit programs — and need no
group objects. This module is the CPU/control-plane tier (the gloo
analogue), e.g. for torch-CPU data-parallel training or coordinating
host-side state.
"""
from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_GROUP_PREFIX = "collective::"

_REDUCERS = {
    "sum": lambda items: np.sum(items, axis=0),
    "prod": lambda items: np.prod(items, axis=0),
    "max": lambda items: np.max(items, axis=0),
    "min": lambda items: np.min(items, axis=0),
    "mean": lambda items: np.mean(items, axis=0),
}


class _GroupActor:
    """Rendezvous + reduction point for one group."""

    def __init__(self, world_size: int):
        self.world = world_size
        self._rounds: Dict[int, Dict[str, Any]] = {}

    def _round(self, seq: int) -> Dict[str, Any]:
        r = self._rounds.get(seq)
        if r is None:
            r = {"items": {}, "event": asyncio.Event(), "result": None}
            self._rounds[seq] = r
        return r

    async def collective(self, seq: int, op: str, rank: int,
                         payload) -> Any:
        r = self._round(seq)
        r["items"][rank] = payload
        if len(r["items"]) == self.world:
            items = [r["items"][k] for k in sorted(r["items"])]
            if op == "barrier":
                r["result"] = None
            elif op == "allgather":
                r["result"] = items
            elif op == "broadcast":
                r["result"] = next(i for i in items if i is not None)
            elif op in _REDUCERS:
                r["result"] = _REDUCERS[op](
                    [np.asarray(i) for i in items])
            else:
                raise ValueError(f"unknown collective op {op!r}")
            r["event"].set()
        await r["event"].wait()
        result = r["result"]
        # Garbage-collect finished rounds lazily.
        self._rounds.pop(seq - 4, None)
        return result

    def world_size(self) -> int:
        return self.world


def create_collective_group(world_size: int, group_name: str = "default"):
    """Create (or get) the named group. Call before members use it."""
    actor_cls = ray_tpu.remote(_GroupActor)
    return actor_cls.options(
        name=_GROUP_PREFIX + group_name, get_if_exists=True,
        num_cpus=0).remote(world_size)


def destroy_collective_group(group_name: str = "default"):
    try:
        h = ray_tpu.get_actor(_GROUP_PREFIX + group_name)
        ray_tpu.kill(h)
    except ValueError:
        pass


class CollectiveGroup:
    """Member-side handle. Each member constructs one with its rank and
    calls the ops in the same order (lockstep sequence numbers)."""

    def __init__(self, rank: int, group_name: str = "default"):
        self.rank = rank
        self.name = group_name
        self._actor = ray_tpu.get_actor(_GROUP_PREFIX + group_name)
        self._seq = 0

    def _call(self, op: str, payload) -> Any:
        seq = self._seq
        self._seq += 1
        return ray_tpu.get(
            self._actor.collective.remote(seq, op, self.rank, payload))

    def allreduce(self, array, op: str = "sum") -> np.ndarray:
        return self._call(op, np.asarray(array))

    def allgather(self, array) -> List[np.ndarray]:
        return self._call("allgather", np.asarray(array))

    def broadcast(self, array, src_rank: int = 0) -> np.ndarray:
        payload = np.asarray(array) if self.rank == src_rank else None
        return self._call("broadcast", payload)

    def barrier(self) -> None:
        self._call("barrier", None)

    def world_size(self) -> int:
        return ray_tpu.get(self._actor.world_size.remote())
