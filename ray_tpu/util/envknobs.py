"""Strict parsers for ray_tpu environment knobs.

Env vars are the last-resort override channel (CI, perf triage, chaos
runs), which is exactly where a silently-ignored typo is most
expensive: ``RAY_TPU_KV_DTYPE=int-8`` falling back to fp would make an
A/B arm measure nothing. Every knob here therefore rejects junk with a
typed error instead of defaulting.

Kept dependency-free (stdlib only): models/ and serve/ both import
this, so it must sit below either package to avoid cycles.
"""
from __future__ import annotations

import os
from typing import Optional

KV_DTYPES = ("fp", "int8")


class EnvKnobError(ValueError):
    """An environment knob is set to a value ray_tpu cannot parse."""

    def __init__(self, name: str, value: str, allowed) -> None:
        self.name = name
        self.value = value
        self.allowed = tuple(allowed)
        super().__init__(
            "%s=%r is not a valid setting (allowed: %s). Unset it or "
            "pick one of the allowed values — junk is rejected rather "
            "than silently defaulted." %
            (name, value, ", ".join(repr(a) for a in self.allowed)))


def parse_bool_knob(name: str, default: bool = False) -> bool:
    """A {unset, "", "0", "1"} switch. "" and unset mean *default*;
    anything else but "0"/"1" is a typed error."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    if raw == "1":
        return True
    if raw == "0":
        return False
    raise EnvKnobError(name, raw, ("", "0", "1"))


def parse_paged_kernel_env(default: bool = False) -> bool:
    """RAY_TPU_PAGED_KERNEL: opt into the pallas decode kernel."""
    return parse_bool_knob("RAY_TPU_PAGED_KERNEL", default)


def parse_kv_dtype_env() -> Optional[str]:
    """RAY_TPU_KV_DTYPE: pool storage dtype override, or None when the
    knob is unset/empty (caller falls back to its constructor arg)."""
    raw = os.environ.get("RAY_TPU_KV_DTYPE")
    if raw is None or raw == "":
        return None
    if raw in KV_DTYPES:
        return raw
    raise EnvKnobError("RAY_TPU_KV_DTYPE", raw, ("",) + KV_DTYPES)


def resolve_kv_dtype(arg: Optional[str]) -> str:
    """Merge the constructor arg with the env override (env wins, so a
    chaos/bench harness can flip a whole fleet without touching code).
    Validates both sides."""
    env = parse_kv_dtype_env()
    if env is not None:
        return env
    if arg is None:
        return "fp"
    if arg not in KV_DTYPES:
        raise ValueError(
            "kv_dtype=%r is not supported (choose one of %s)" %
            (arg, ", ".join(repr(d) for d in KV_DTYPES)))
    return arg
