"""Application metrics API.

Capability parity with the reference's ray.util.metrics
(python/ray/util/metrics.py Counter/Gauge/Histogram over the opencensus
pipeline, src/ray/stats/metric.h DEFINE_stats): a process-local registry
with tag support and Prometheus text exposition (served by the dashboard).
"""
from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}
# Optional node-wide shared-memory sink (the native stats substrate,
# src/metrics/shm_metrics.cc): when attached — worker processes attach
# at bootstrap — every record also lands in the shm segment so the head
# aggregates across processes without RPC.
_shm_registry = None


def set_shm_registry(reg) -> None:
    global _shm_registry
    _shm_registry = reg


def get_shm_registry():
    return _shm_registry


def _shm_key(name: str, tags: tuple) -> str:
    from ray_tpu._private.shm_metrics import metric_key
    return metric_key(name, dict(tags))


def registry() -> Dict[str, "Metric"]:
    return dict(_registry)


def clear_registry():
    with _registry_lock:
        _registry.clear()


class Metric:
    TYPE = "untyped"
    # Tag keys the exposition format itself claims for this type —
    # user labels must not shadow them (e.g. "le" on histograms).
    RESERVED_TAG_KEYS: Tuple[str, ...] = ()

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        if len(set(self.tag_keys)) != len(self.tag_keys):
            raise ValueError(
                f"metric {name!r}: duplicate tag keys "
                f"{list(self.tag_keys)}")
        for reserved in self.RESERVED_TAG_KEYS:
            if reserved in self.tag_keys:
                raise ValueError(
                    f"metric {name!r}: tag key {reserved!r} is "
                    f"reserved by the {self.TYPE} exposition format")
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            prior = _registry.get(name)
            if prior is not None and (
                    prior.TYPE != self.TYPE
                    or prior.tag_keys != self.tag_keys):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{prior.TYPE}{list(prior.tag_keys)}; refusing "
                    f"colliding re-registration as "
                    f"{self.TYPE}{list(self.tag_keys)} — a merged "
                    f"scrape would expose two families under one "
                    f"name")
            _registry[name] = self

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _resolve_tags(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(
                f"Unknown tags {sorted(extra)} for metric "
                f"{self.name!r} (declared: {self.tag_keys})")
        return tuple(sorted(merged.items()))

    def _samples(self) -> List[Tuple[Tuple, Any]]:
        raise NotImplementedError


class Counter(Metric):
    TYPE = "counter"

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter increments must be >= 0")
        key = self._resolve_tags(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value
        if _shm_registry is not None:
            _shm_registry.counter_add(_shm_key(self.name, key), value)

    def _samples(self):
        with self._lock:
            return list(self._values.items())


class Gauge(Metric):
    TYPE = "gauge"

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._resolve_tags(tags)
        with self._lock:
            self._values[key] = float(value)
        if _shm_registry is not None:
            _shm_registry.gauge_set(_shm_key(self.name, key), value)

    def _samples(self):
        with self._lock:
            return list(self._values.items())


class Histogram(Metric):
    TYPE = "histogram"
    RESERVED_TAG_KEYS = ("le",)

    def __init__(self, name, description="",
                 boundaries: Sequence[float] = (), tag_keys=()):
        super().__init__(name, description, tag_keys)
        if not boundaries or list(boundaries) != sorted(boundaries):
            raise ValueError("boundaries must be a sorted non-empty list")
        self.boundaries = list(boundaries)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        key = self._resolve_tags(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1
        if _shm_registry is not None:
            _shm_registry.histogram_observe(_shm_key(self.name, key),
                                            value)

    def _samples(self):
        with self._lock:
            return [(k, {"counts": list(v),
                         "sum": self._sums[k],
                         "count": self._totals[k]})
                    for k, v in self._counts.items()]


def _escape_label(v) -> str:
    """Prometheus label-value escaping (exposition format spec):
    backslash, double-quote, and newline must be escaped or a value
    like 'say "hi"' corrupts every line after it."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_tags(tags: Tuple) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in tags)
    return "{" + inner + "}"


def prometheus_text() -> str:
    """Prometheus exposition format for every registered metric.

    Deterministic: families sort by name and samples by their tag
    tuple, so two scrapes of the same state are byte-identical and a
    multi-process merged scrape is diffable."""
    lines: List[str] = []
    for _, m in sorted(registry().items()):
        lines.append(f"# HELP {m.name} {m.description}")
        lines.append(f"# TYPE {m.name} {m.TYPE}")
        for tags, value in sorted(m._samples(), key=lambda kv: kv[0]):
            if isinstance(m, Histogram):
                cum = 0
                for bound, c in zip(m.boundaries + [float("inf")],
                                    value["counts"]):
                    cum += c
                    b = "+Inf" if bound == float("inf") else repr(bound)
                    tag_str = _fmt_tags(tags + (("le", b),))
                    lines.append(f"{m.name}_bucket{tag_str} {cum}")
                lines.append(
                    f"{m.name}_sum{_fmt_tags(tags)} {value['sum']}")
                lines.append(
                    f"{m.name}_count{_fmt_tags(tags)} {value['count']}")
            else:
                lines.append(f"{m.name}{_fmt_tags(tags)} {value}")
    return "\n".join(lines) + "\n"
