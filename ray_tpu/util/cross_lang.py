"""Cross-language function descriptors + registry.

Capability parity with the reference's cross-language path
(src/ray/core_worker — C++/Java task specs name functions by
DESCRIPTOR, not by pickled closure; the receiving worker resolves the
descriptor against its own runtime). Descriptors here:

- ``import://module:attr`` — resolved by import on the executing
  worker (any importable callable; the form the C++ client's
  ``Submit`` emits, src/cpp_api/raytpu_client.cc);
- ``registry://name`` — resolved against the process-local registry
  populated via :func:`register_function` (lets non-Python clients
  call short stable names without knowing module layout);
- a bare ``module:attr`` string is treated as ``import://``.

Args and return values must be plain data (None/bool/int/float/str/
bytes/list/tuple/dict) — the C++ pickle codec rejects code objects by
design. ``validate_args`` enforces the same contract Python-side so a
bad payload fails at the boundary with a clear error instead of deep
inside the codec.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List

_REGISTRY: Dict[str, Callable] = {}
_REG_LOCK = threading.Lock()

_PLAIN = (type(None), bool, int, float, str, bytes)


def register_function(name: str, fn: Callable) -> None:
    """Expose `fn` to cross-language callers as ``registry://name``.
    Call at import time in any module the worker loads (e.g. via
    runtime_env py_modules) — registration is per-process."""
    if not callable(fn):
        raise TypeError(f"{fn!r} is not callable")
    with _REG_LOCK:
        _REGISTRY[name] = fn


def registered_functions() -> List[str]:
    with _REG_LOCK:
        return sorted(_REGISTRY)


def resolve_descriptor(descriptor: str) -> Callable:
    """Descriptor -> callable on THIS worker. Raises LookupError with
    the known-name list for registry misses (the error a foreign
    client sees in its task result)."""
    if descriptor.startswith("registry://"):
        name = descriptor[len("registry://"):]
        with _REG_LOCK:
            fn = _REGISTRY.get(name)
        if fn is None:
            raise LookupError(
                f"no registered cross-language function {name!r} "
                f"(known: {registered_functions()})")
        return fn
    if descriptor.startswith("import://"):
        descriptor = descriptor[len("import://"):]
    mod_name, sep, attr = descriptor.partition(":")
    if not sep or not mod_name or not attr:
        raise ValueError(
            f"bad cross-language descriptor {descriptor!r}; expected "
            f"'module:attr', 'import://module:attr' or "
            f"'registry://name'")
    import importlib
    obj: Any = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    if not callable(obj):
        raise TypeError(f"{descriptor!r} resolves to non-callable "
                        f"{type(obj).__name__}")
    return obj


def validate_args(value: Any, _depth: int = 0) -> None:
    """Enforce the plain-data contract (mirrors the C++ codec,
    src/cpp_api/pickle.cc): descriptive TypeError instead of a codec
    rejection deep in the stack."""
    if _depth > 32:
        raise TypeError("cross-language value nests too deeply")
    if isinstance(value, _PLAIN):
        return
    if isinstance(value, (list, tuple)):
        for v in value:
            validate_args(v, _depth + 1)
        return
    if isinstance(value, dict):
        for k, v in value.items():
            validate_args(k, _depth + 1)
            validate_args(v, _depth + 1)
        return
    raise TypeError(
        f"cross-language values must be plain data "
        f"(None/bool/int/float/str/bytes/list/tuple/dict); got "
        f"{type(value).__name__}")


# --------------------------------------------------------------------------
# In-repo example targets (used by the C++ demo and tests).
# --------------------------------------------------------------------------

def square(x: int) -> int:
    return x * x


def describe(xs: List[float]) -> Dict[str, Any]:
    xs = list(xs)
    return {"n": len(xs), "sum": float(sum(xs)),
            "min": min(xs), "max": max(xs)}


def echo(value: Any) -> Any:
    return value


def boom() -> None:
    raise RuntimeError("cross-lang failure example")


register_function("square", square)
register_function("describe", describe)
register_function("echo", echo)
