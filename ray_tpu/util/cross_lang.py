"""Cross-language task targets (reference: the cross_language function
descriptors C++/Java tasks name, src/ray/core_worker cross-language
path). Any importable "module:function" works as a C++ `Submit`
target; these are the in-repo examples the demo and tests use. Args
and return values must be plain data (None/bool/int/float/str/bytes/
list/tuple/dict) — the C++ pickle codec rejects code objects by
design."""
from __future__ import annotations

from typing import Any, Dict, List


def square(x: int) -> int:
    return x * x


def describe(xs: List[float]) -> Dict[str, Any]:
    xs = list(xs)
    return {"n": len(xs), "sum": float(sum(xs)),
            "min": min(xs), "max": max(xs)}


def echo(value: Any) -> Any:
    return value


def boom() -> None:
    raise RuntimeError("cross-lang failure example")
