from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (placement_group,
                                          remove_placement_group)
from ray_tpu.util.queue import Queue
# Submodules reachable as attributes (reference: ray.util.metrics /
# ray.util.collective / ray.util.iter usage style).
from ray_tpu.util import (collective, iter, metrics,  # noqa: F401,A004
                          tracing)
from ray_tpu._private.task_spec import (
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SliceAffinitySchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "ActorPool", "Queue",
    "placement_group", "remove_placement_group",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "SpreadSchedulingStrategy", "DefaultSchedulingStrategy",
    "SliceAffinitySchedulingStrategy",
    # Submodules (collective/iter/metrics/tracing) stay reachable as
    # attributes but are deliberately NOT in __all__: star-importing a
    # module named `iter` would shadow the builtin.
]
