from ray_tpu.util.placement_group import (placement_group,
                                          remove_placement_group)
from ray_tpu._private.task_spec import (
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SliceAffinitySchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "placement_group", "remove_placement_group",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "SpreadSchedulingStrategy", "DefaultSchedulingStrategy",
    "SliceAffinitySchedulingStrategy",
]
