"""Placement groups: gang resource reservation.

Capability parity with the reference (python/ray/util/placement_group.py;
2PC reservation src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h).
TPU-native addition: bundles may request ``TPU`` and carry an
``ici_topology`` hint so the distributed scheduler reserves whole ICI
sub-slices (STRICT_PACK == same ICI domain, see SURVEY.md §7).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.task_spec import Bundle, PlacementGroupSpec
from ray_tpu._private.worker import global_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None,
                    _ici_topology: Optional[str] = None):
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b!r}")
    spec = PlacementGroupSpec(
        pg_id=PlacementGroupID.from_random(),
        bundles=[Bundle(resources=dict(b), index=i)
                 for i, b in enumerate(bundles)],
        strategy=strategy,
        name=name,
        lifetime=lifetime,
    )
    return global_worker().runtime.create_placement_group(spec)


def remove_placement_group(pg) -> None:
    global_worker().runtime.remove_placement_group(pg)
