"""Dask-on-ray_tpu: execute dask-protocol graphs and delayed trees
as runtime tasks.

Capability parity with the reference's dask scheduler
(python/ray/util/dask/scheduler.py `ray_dask_get`): a drop-in dask
``get`` that walks the standard dask graph protocol — dicts mapping
keys to tasks ``(callable, *args)``, where args may be other keys,
nested lists, or literals — submitting one runtime task per graph
node with shared nodes computed ONCE. The dask package itself is not
required: the graph protocol is plain dicts/tuples, so existing dask
graphs (or hand-written ones) run as-is; when dask IS importable,
pass ``get=ray_dask_get`` to ``dask.compute`` exactly like the
reference.

Also provides a ``delayed`` decorator (dask.delayed-style lazy call
trees) for users who want the ergonomic API without dask.
"""
from __future__ import annotations

from typing import Any, Dict, Hashable, List

__all__ = ["ray_dask_get", "delayed", "Delayed"]


def _exec_node(fn, args_tree):
    """Worker-side: resolve nested ObjectRefs, then call."""
    import ray_tpu
    from ray_tpu._private.object_ref import ObjectRef

    def resolve(x):
        if isinstance(x, ObjectRef):
            return ray_tpu.get(x)
        if isinstance(x, list):
            return [resolve(v) for v in x]
        if isinstance(x, tuple):
            return tuple(resolve(v) for v in x)
        if isinstance(x, dict):
            return {k: resolve(v) for k, v in x.items()}
        return x

    return fn(*resolve(list(args_tree)))


def _is_task(expr) -> bool:
    return (isinstance(expr, tuple) and expr
            and callable(expr[0]))


def ray_dask_get(dsk: Dict[Hashable, Any], keys, **kwargs):
    """Execute a dask graph; returns values matching `keys` (which may
    be a single key, or arbitrarily nested lists of keys, per the dask
    scheduler contract)."""
    import ray_tpu
    refs: Dict[Hashable, Any] = {}

    def submit(key, stack=()):
        if key in refs:
            return refs[key]
        if key in stack:
            raise ValueError(f"cycle detected at key {key!r}")
        expr = dsk[key]
        ref = _submit_expr(expr, stack + (key,))
        refs[key] = ref
        return ref

    def translate(term, stack):
        """Graph term -> task argument: keys become refs, nested
        containers recurse, everything else is a literal."""
        if _is_task(term):
            return _submit_expr(term, stack)
        try:
            if term in dsk:            # a key reference
                return submit(term, stack)
        except TypeError:
            pass                       # unhashable: literal container
        if isinstance(term, list):
            return [translate(t, stack) for t in term]
        if isinstance(term, tuple):
            return tuple(translate(t, stack) for t in term)
        if isinstance(term, dict):
            return {k: translate(v, stack) for k, v in term.items()}
        return term

    def _submit_expr(expr, stack):
        if _is_task(expr):
            fn, *args = expr
            task_args = [translate(a, stack) for a in args]
            return ray_tpu.remote(_exec_node).remote(fn, task_args)
        # alias / literal node
        translated = translate(expr, stack)
        from ray_tpu._private.object_ref import ObjectRef
        if isinstance(translated, ObjectRef):
            return translated
        return ray_tpu.put(translated)

    def gather(ks):
        if isinstance(ks, list):
            return [gather(k) for k in ks]
        return ray_tpu.get(submit(ks))

    return gather(keys)


class Delayed:
    """A lazy call node (dask.delayed-style). Build trees by calling
    @delayed functions with Delayed arguments; .compute() executes the
    tree as runtime tasks, computing shared nodes once."""

    __slots__ = ("_fn", "_args", "_kwargs")

    def __init__(self, fn, args, kwargs):
        self._fn = fn
        self._args = args
        self._kwargs = kwargs

    def compute(self):
        return compute(self)[0]

    def __repr__(self):
        return f"Delayed({getattr(self._fn, '__name__', self._fn)})"


def delayed(fn):
    def make(*args, **kwargs):
        return Delayed(fn, args, kwargs)
    make.__name__ = getattr(fn, "__name__", "delayed")
    return make


def compute(*nodes):
    """Execute Delayed trees; shared sub-nodes run once."""
    import ray_tpu
    memo: Dict[int, Any] = {}

    def submit(node):
        if id(node) in memo:
            return memo[id(node)]

        def translate(x):
            if isinstance(x, Delayed):
                return submit(x)
            if isinstance(x, list):
                return [translate(v) for v in x]
            if isinstance(x, tuple):
                return tuple(translate(v) for v in x)
            if isinstance(x, dict):
                return {k: translate(v) for k, v in x.items()}
            return x

        args = [translate(a) for a in node._args]
        kw = {k: translate(v) for k, v in node._kwargs.items()}
        fn = node._fn
        if kw:
            import functools
            fn = functools.partial(fn, **kw)
        ref = ray_tpu.remote(_exec_node).remote(fn, args)
        memo[id(node)] = ref
        return ref

    return [ray_tpu.get(submit(n)) for n in nodes]
