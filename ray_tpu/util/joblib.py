"""joblib backend: run sklearn/joblib workloads on the cluster.

Capability parity with the reference's joblib integration
(python/ray/util/joblib/__init__.py + ray_backend.py): after
``register_ray()``, ``with joblib.parallel_backend("ray_tpu"):`` routes
every joblib batch to a remote task, so ``GridSearchCV`` et al. fan out
across the cluster instead of local processes.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

import ray_tpu

__all__ = ["register_ray"]


def _run_joblib_batch(batch):
    return batch()


class _RayFuture:
    """Future-ish wrapper joblib expects from ``apply_async``."""

    def __init__(self, ref, callback: Optional[Callable]):
        self._ref = ref
        self._value = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

        def waiter():
            try:
                self._value = ray_tpu.get(ref)
            except BaseException as e:  # noqa: BLE001
                self._error = e
            self._done.set()
            if callback is not None and self._error is None:
                callback(self._value)

        threading.Thread(target=waiter, daemon=True).start()

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("joblib batch not finished")
        if self._error is not None:
            raise self._error
        return self._value


def register_ray() -> None:
    """Register the ``ray_tpu`` joblib parallel backend."""
    from joblib import register_parallel_backend
    from joblib._parallel_backends import (AutoBatchingMixin,
                                           ParallelBackendBase)

    class RayTpuBackend(AutoBatchingMixin, ParallelBackendBase):
        supports_timeout = True

        def configure(self, n_jobs=1, parallel=None, **_):
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs):
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs == -1 or n_jobs is None:
                return max(1, cpus)
            return max(1, n_jobs)

        def apply_async(self, func, callback=None):
            ref = ray_tpu.remote(_run_joblib_batch).remote(func)
            return _RayFuture(ref, callback)

        # joblib >= 1.4 prefers submit(); same contract.
        def submit(self, func, callback=None):
            return self.apply_async(func, callback)

        def retrieve_result_callback(self, out):
            return out.get()

        def abort_everything(self, ensure_ready=True):
            if ensure_ready:
                self.configure(n_jobs=self.parallel.n_jobs,
                               parallel=self.parallel)

    register_parallel_backend("ray_tpu", RayTpuBackend)
