"""ParallelIterator: sharded, lazily-transformed distributed iterators.

Capability parity with the reference's ``ray.util.iter``
(python/ray/util/iter.py — ``from_items``/``from_range``/
``from_iterators``, ``for_each``/``filter``/``batch``/``flatten``,
``gather_sync``/``gather_async``, ``union``, ``take``/``show``), which
RLlib's execution plans were originally built on.

Fresh design: each shard is an actor holding an iterator factory; the
transformation chain is shipped to the actor and applied lazily inside it,
so pulled items cross process boundaries exactly once, post-transform.
"""
from __future__ import annotations

import uuid
from typing import Any, Callable, Iterable, List, Tuple

import ray_tpu

__all__ = ["from_items", "from_range", "from_iterators", "ParallelIterator",
           "LocalIterator"]

_SENTINEL = "__ray_tpu_iter_stop__"


def _apply_ops(it, ops):
    for kind, fn in ops:
        if kind == "for_each":
            it = map(fn, it)
        elif kind == "filter":
            it = filter(fn, it)
        elif kind == "batch":
            it = _batched(it, fn)
        elif kind == "flatten":
            it = (x for chunk in it for x in chunk)
        else:
            raise ValueError(f"unknown op {kind}")
    return it


def _batched(it, n):
    buf = []
    for x in it:
        buf.append(x)
        if len(buf) == n:
            yield buf
            buf = []
    if buf:
        yield buf


@ray_tpu.remote
class _ShardActor:
    """Holds the iterator factory; every gather opens an independent
    stream (keyed by id), so a base iterator, its derivations, and unions
    can be consumed concurrently without corrupting each other."""

    def __init__(self, creator: Callable[[], Iterable]):
        self._creator = creator
        self._streams = {}

    def start(self, stream_id: str, ops: List[Tuple[str, Any]],
              repeat: bool):
        def gen():
            while True:
                yield from _apply_ops(iter(self._creator()), ops)
                if not repeat:
                    return
        self._streams[stream_id] = gen()
        return True

    def next(self, stream_id: str):
        it = self._streams.get(stream_id)
        if it is None:  # already exhausted (possible with num_async > 1)
            return _SENTINEL
        try:
            return next(it)
        except StopIteration:
            self._streams.pop(stream_id, None)
            return _SENTINEL


class ParallelIterator:
    def __init__(self, shards: List[Tuple[Any, List, bool]]):
        # each shard: (actor, ops, repeat)
        self._shards = shards

    # -- lazy transforms ---------------------------------------------------

    def _with_op(self, kind: str, arg) -> "ParallelIterator":
        return ParallelIterator(
            [(a, ops + [(kind, arg)], rep) for a, ops, rep in self._shards])

    def for_each(self, fn: Callable) -> "ParallelIterator":
        return self._with_op("for_each", fn)

    def filter(self, fn: Callable) -> "ParallelIterator":
        return self._with_op("filter", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return self._with_op("batch", n)

    def flatten(self) -> "ParallelIterator":
        return self._with_op("flatten", None)

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        return ParallelIterator(self._shards + other._shards)

    def num_shards(self) -> int:
        return len(self._shards)

    # -- gathering ---------------------------------------------------------

    def gather_sync(self) -> "LocalIterator":
        """Round-robin over shards, one item at a time, in order."""
        shards = self._shards

        def gen():
            sid = uuid.uuid4().hex
            ray_tpu.get([a.start.remote(sid, ops, rep)
                         for a, ops, rep in shards])
            live = [a for a, _, _ in shards]
            while live:
                for a in list(live):
                    item = ray_tpu.get(a.next.remote(sid))
                    if isinstance(item, str) and item == _SENTINEL:
                        live.remove(a)
                    else:
                        yield item
        return LocalIterator(gen)

    def gather_async(self, num_async: int = 1) -> "LocalIterator":
        """Pull from all shards concurrently; yield in completion order."""
        shards = self._shards

        def gen():
            sid = uuid.uuid4().hex
            ray_tpu.get([a.start.remote(sid, ops, rep)
                         for a, ops, rep in shards])
            inflight = {}
            for a, _, _ in shards:
                for _ in range(num_async):
                    inflight[a.next.remote(sid)] = a
            while inflight:
                ready, _ = ray_tpu.wait(list(inflight), num_returns=1)
                ref = ready[0]
                a = inflight.pop(ref)
                item = ray_tpu.get(ref)
                if isinstance(item, str) and item == _SENTINEL:
                    continue
                inflight[a.next.remote(sid)] = a
                yield item
        return LocalIterator(gen)

    # -- conveniences ------------------------------------------------------

    def take(self, n: int) -> List[Any]:
        out = []
        for x in self.gather_sync():
            out.append(x)
            if len(out) >= n:
                break
        return out

    def show(self, n: int = 20) -> None:
        for x in self.take(n):
            print(x)

    def __repr__(self):
        return f"ParallelIterator(shards={len(self._shards)})"


class LocalIterator:
    """A single-process iterator view with chainable local transforms."""

    def __init__(self, gen_factory: Callable[[], Iterable]):
        self._factory = gen_factory

    def for_each(self, fn) -> "LocalIterator":
        f = self._factory
        return LocalIterator(lambda: map(fn, f()))

    def filter(self, fn) -> "LocalIterator":
        f = self._factory
        return LocalIterator(lambda: filter(fn, f()))

    def batch(self, n) -> "LocalIterator":
        f = self._factory
        return LocalIterator(lambda: _batched(f(), n))

    def take(self, n) -> List[Any]:
        out = []
        for x in self:
            out.append(x)
            if len(out) >= n:
                break
        return out

    def __iter__(self):
        return iter(self._factory())


def from_iterators(creators: List[Callable[[], Iterable]],
                   repeat: bool = False) -> ParallelIterator:
    """One shard per iterator factory."""
    shards = [(_ShardActor.remote(c), [], repeat) for c in creators]
    return ParallelIterator(shards)


def from_items(items: List[Any], num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    chunks: List[List[Any]] = [[] for _ in range(num_shards)]
    for i, x in enumerate(items):
        chunks[i % num_shards].append(x)
    return from_iterators(
        [(lambda c=c: iter(c)) for c in chunks], repeat=repeat)


def from_range(n: int, num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    return from_items(list(range(n)), num_shards=num_shards, repeat=repeat)
