"""Public scheduling strategies (reference:
python/ray/util/scheduling_strategies.py:15,41). Implementations live
with the task spec; the head's policy dispatch is
runtime/head.py _pick_worker_locked."""
from ray_tpu._private.task_spec import (  # noqa: F401
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SchedulingStrategy,
    SliceAffinitySchedulingStrategy,
    SpreadSchedulingStrategy,
)
