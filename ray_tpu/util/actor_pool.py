"""ActorPool (reference: python/ray/util/actor_pool.py): load-balance
function applications over a fixed set of actors."""
from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        if not actors:
            raise ValueError("ActorPool requires at least one actor")
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending = []
        self._results = []
        self._index = 0

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (self._index, actor)
            self._index += 1
        else:
            self._pending.append((fn, value))

    def _drain_pending(self):
        while self._pending and self._idle:
            fn, value = self._pending.pop(0)
            self.submit(fn, value)

    def has_next(self) -> bool:
        return bool(self._future_to_actor or self._pending)

    def get_next_unordered(self, timeout=None):
        if not self.has_next():
            raise StopIteration("No pending results")
        refs = list(self._future_to_actor)
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        _, actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        self._drain_pending()
        return ray_tpu.get(ref)

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def map(self, fn: Callable, values: Iterable[Any]):
        values = list(values)
        for v in values:
            self.submit(fn, v)
        out = {}
        while self.has_next():
            refs = list(self._future_to_actor)
            ready, _ = ray_tpu.wait(refs, num_returns=1)
            ref = ready[0]
            idx, actor = self._future_to_actor.pop(ref)
            self._idle.append(actor)
            self._drain_pending()
            out[idx] = ray_tpu.get(ref)
        return [out[i] for i in sorted(out)]
