"""Durable workflow storage (filesystem backend).

Capability parity with the reference's workflow storage
(python/ray/workflow/workflow_storage.py): per-workflow directory holding the
serialized DAG state, one result file per completed step, and a status
marker. Writes are atomic (tmp + rename) so a crash mid-write never corrupts
a step result — this is what makes resume exactly-once-ish.

Layout::

    {base}/{workflow_id}/state.pkl        # serialized step graph + input
    {base}/{workflow_id}/status           # RUNNING/SUCCESSFUL/FAILED/...
    {base}/{workflow_id}/steps/{id}.pkl   # completed step results
    {base}/{workflow_id}/output.pkl       # final workflow output
"""
from __future__ import annotations

import os
import shutil
import tempfile
from typing import Any, List, Optional

import cloudpickle as pickle

_DEFAULT_BASE = os.path.join(tempfile.gettempdir(), "ray_tpu", "workflows")


def _atomic_write(path: str, data: bytes) -> None:
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class WorkflowStorage:
    def __init__(self, base_dir: Optional[str] = None):
        self.base = base_dir or _DEFAULT_BASE
        os.makedirs(self.base, exist_ok=True)

    # -- workflow-level ----------------------------------------------------

    def _wf_dir(self, workflow_id: str) -> str:
        if not workflow_id or "/" in workflow_id or workflow_id.startswith("."):
            raise ValueError(f"invalid workflow id: {workflow_id!r}")
        return os.path.join(self.base, workflow_id)

    def exists(self, workflow_id: str) -> bool:
        return os.path.isdir(self._wf_dir(workflow_id))

    def list_workflows(self) -> List[str]:
        return sorted(
            d for d in os.listdir(self.base)
            if os.path.isdir(os.path.join(self.base, d)))

    def delete(self, workflow_id: str) -> None:
        shutil.rmtree(self._wf_dir(workflow_id), ignore_errors=True)

    def save_state(self, workflow_id: str, state: Any) -> None:
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "state.pkl"),
                      pickle.dumps(state))

    def load_state(self, workflow_id: str) -> Any:
        with open(os.path.join(self._wf_dir(workflow_id), "state.pkl"),
                  "rb") as f:
            return pickle.load(f)

    def set_status(self, workflow_id: str, status: str) -> None:
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "status"),
                      status.encode())

    def get_status(self, workflow_id: str) -> Optional[str]:
        try:
            with open(os.path.join(self._wf_dir(workflow_id), "status"),
                      "rb") as f:
                return f.read().decode()
        except FileNotFoundError:
            return None

    # -- step-level --------------------------------------------------------

    def _step_path(self, workflow_id: str, step_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "steps",
                            f"{step_id}.pkl")

    def has_step(self, workflow_id: str, step_id: str) -> bool:
        return os.path.exists(self._step_path(workflow_id, step_id))

    def save_step_result(self, workflow_id: str, step_id: str,
                         value: Any) -> None:
        _atomic_write(self._step_path(workflow_id, step_id),
                      pickle.dumps(value))

    def load_step_result(self, workflow_id: str, step_id: str) -> Any:
        with open(self._step_path(workflow_id, step_id), "rb") as f:
            return pickle.load(f)

    # -- output ------------------------------------------------------------

    def save_output(self, workflow_id: str, value: Any) -> None:
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "output.pkl"),
                      pickle.dumps(value))

    def load_output(self, workflow_id: str) -> Any:
        with open(os.path.join(self._wf_dir(workflow_id), "output.pkl"),
                  "rb") as f:
            return pickle.load(f)

    def has_output(self, workflow_id: str) -> bool:
        return os.path.exists(
            os.path.join(self._wf_dir(workflow_id), "output.pkl"))
