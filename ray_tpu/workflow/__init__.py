"""Durable workflows: persistent, resumable DAG execution.

Capability parity with the reference workflow library
(python/ray/workflow/{api,workflow_executor,workflow_state_from_dag}.py):
a DAG built with ``.bind()`` is converted to a serializable step graph,
persisted to storage, and executed with each step's result checkpointed as
it completes. ``resume()`` reloads the graph and skips completed steps, so
a crashed workflow continues where it left off (exactly-once per step, at
the granularity of the atomic result write).

Fresh design notes: steps run as ordinary remote tasks with a generic
runner; the driver-side event loop submits every dependency-ready step
concurrently (the reference threads continuations through an executor
actor instead). Virtual actors are out of scope, as in the reference's
DAG-based API.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.dag import (DAGNode, FunctionNode, InputAttributeNode,
                         InputNode, MultiOutputNode, _scan)
from ray_tpu.workflow.storage import WorkflowStorage

__all__ = ["init", "run", "run_async", "resume", "resume_all",
           "cancel", "continuation", "WorkflowCancelledError",
           "get_status", "get_output", "list_all", "delete",
           "WorkflowStatus"]


class WorkflowCancelledError(RuntimeError):
    """The workflow was cancelled via workflow.cancel()."""

    def __init__(self, workflow_id: str):
        self.workflow_id = workflow_id
        super().__init__(f"workflow {workflow_id!r} was cancelled")


class WorkflowStatus:
    CANCELED = "CANCELED"
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


_storage: Optional[WorkflowStorage] = None


def init(storage_dir: Optional[str] = None) -> None:
    """Point the workflow engine at a storage directory."""
    global _storage
    _storage = WorkflowStorage(storage_dir)


def _get_storage() -> WorkflowStorage:
    global _storage
    if _storage is None:
        _storage = WorkflowStorage()
    return _storage


# ---------------------------------------------------------------------------
# DAG -> serializable step graph
# ---------------------------------------------------------------------------

class _StepRef:
    """Placeholder for another step's output inside bound args."""

    def __init__(self, step_id: str):
        self.step_id = step_id


class _InputRef:
    """Placeholder for (a projection of) the workflow input."""

    def __init__(self, kind: str = "whole", key: Any = None):
        self.kind = kind  # whole | item | attr
        self.key = key


class _StepSpec:
    def __init__(self, step_id: str, func, args, kwargs, options,
                 is_output_list: bool = False):
        self.step_id = step_id
        self.func = func  # None for MultiOutputNode
        self.args = args
        self.kwargs = kwargs
        self.options = options or {}
        self.is_output_list = is_output_list

    def dependencies(self) -> List[str]:
        deps: List[str] = []

        def visit(v):
            if isinstance(v, _StepRef):
                deps.append(v.step_id)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    visit(x)
            elif isinstance(v, dict):
                for x in v.values():
                    visit(x)

        visit(self.args)
        visit(self.kwargs)
        return deps


class _WorkflowState:
    """The persisted object: every step plus the terminal step id and the
    pickled workflow input."""

    def __init__(self, steps: Dict[str, _StepSpec], output_step: str,
                 input_args: Tuple, input_kwargs: Dict[str, Any]):
        self.steps = steps
        self.output_step = output_step
        self.input_args = input_args
        self.input_kwargs = input_kwargs


def _state_from_dag(dag: DAGNode, input_args, input_kwargs) -> _WorkflowState:
    steps: Dict[str, _StepSpec] = {}
    memo: Dict[str, Any] = {}  # node uuid -> placeholder value

    def convert(node: DAGNode):
        if node._stable_uuid in memo:
            return memo[node._stable_uuid]
        if isinstance(node, InputNode):
            out = _InputRef("whole")
        elif isinstance(node, InputAttributeNode):
            out = _InputRef(node._kind, node._key)
        elif isinstance(node, MultiOutputNode):
            inner = [_convert_value(v) for v in node._bound_args[0]]
            sid = f"output-{node._stable_uuid[:8]}"
            steps[sid] = _StepSpec(sid, None, (inner,), {}, {},
                                   is_output_list=True)
            out = _StepRef(sid)
        elif isinstance(node, FunctionNode):
            args = _convert_value(node._bound_args)
            kwargs = _convert_value(node._bound_kwargs)
            fn = node._remote_fn
            name = getattr(fn, "__name__", "step")
            sid = f"{name}-{node._stable_uuid[:8]}"
            # Decorator-level options (resources, retries) carry into the
            # step; node-level .options() overrides them, matching what
            # FunctionNode._execute_impl does on the non-durable path.
            opts = {**fn._options, **node._bound_options}
            steps[sid] = _StepSpec(sid, fn._func, args, kwargs, opts)
            out = _StepRef(sid)
        else:
            raise TypeError(
                f"Durable workflows support function DAGs only; got "
                f"{type(node).__name__} (actor nodes are not "
                f"checkpointable)")
        memo[node._stable_uuid] = out
        return out

    def _convert_value(v):
        return _scan(v, convert)

    terminal = convert(dag)
    if not isinstance(terminal, _StepRef):
        raise TypeError("workflow DAG must terminate in a function step")
    return _WorkflowState(steps, terminal.step_id, tuple(input_args),
                          dict(input_kwargs))


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------

def _project_input(ref: _InputRef, input_args, input_kwargs):
    if ref.kind == "whole":
        if not input_args and input_kwargs:
            raise TypeError("workflow input was kwargs-only; access it "
                            "via InputAttributeNode (inp['key']), not "
                            "bare InputNode")
        if len(input_args) == 1:
            return input_args[0]
        return input_args if input_args else None
    if ref.kind == "item":
        if input_kwargs and isinstance(ref.key, str) \
                and ref.key in input_kwargs:
            return input_kwargs[ref.key]
        base = _project_input(_InputRef("whole"), input_args, input_kwargs)
        return base[ref.key]
    if input_kwargs and ref.key in input_kwargs:
        return input_kwargs[ref.key]
    base = _project_input(_InputRef("whole"), input_args, input_kwargs)
    return getattr(base, ref.key)


def _run_step(func, args, kwargs):
    return func(*args, **kwargs)


class _Continuation:
    """A step's request to expand into a sub-workflow (reference:
    workflow.continuation — the step's return value becomes the
    sub-DAG's output; enables recursion/loops in durable DAGs)."""

    def __init__(self, dag: DAGNode):
        self.dag = dag


def continuation(dag: DAGNode) -> "_Continuation":
    """Return this from a workflow step to continue into ``dag``: the
    engine expands the sub-DAG in place, persisting each sub-step, and
    the step's consumers receive the sub-DAG's output. Sub-step ids
    derive from the parent step id + structural position, so a resumed
    workflow re-expands deterministically and reuses sub-step
    checkpoints (assumes the step builds the same DAG on re-run, the
    reference's assumption too)."""
    if not isinstance(dag, DAGNode):
        raise TypeError(
            f"continuation() takes a bound DAG node, got "
            f"{type(dag).__name__}")
    return _Continuation(dag)


def _expand_continuation(state: "_WorkflowState", parent_sid: str,
                         cont: _Continuation
                         ) -> Tuple[str, List[str]]:
    """Merge cont's sub-DAG into the running state under
    deterministic ids; returns (sub-output step id the parent aliases
    to, all new step ids). Ids stay BOUNDED under recursion: a long
    parent id collapses to its digest, so depth-10k loops neither
    nest checkpoint directories nor exceed NAME_MAX."""
    import hashlib
    sub = _state_from_dag(cont.dag, state.input_args,
                          state.input_kwargs)
    prefix = parent_sid if len(parent_sid) <= 48 else \
        "c" + hashlib.sha1(parent_sid.encode()).hexdigest()[:16]
    mapping: Dict[str, str] = {}
    for idx, old_sid in enumerate(sub.steps):
        name = old_sid.rsplit("-", 1)[0]
        mapping[old_sid] = f"{prefix}~{idx}-{name}"

    def rename(v):
        if isinstance(v, _StepRef):
            return _StepRef(mapping[v.step_id])
        if isinstance(v, list):
            return [rename(x) for x in v]
        if isinstance(v, tuple):
            return tuple(rename(x) for x in v)
        if isinstance(v, dict):
            return {k: rename(x) for k, x in v.items()}
        return v

    for old_sid, spec in sub.steps.items():
        new_sid = mapping[old_sid]
        state.steps[new_sid] = _StepSpec(
            new_sid, spec.func, rename(spec.args),
            rename(spec.kwargs), spec.options,
            is_output_list=spec.is_output_list)
    return mapping[sub.output_step], list(mapping.values())


def _execute_state(state: _WorkflowState, workflow_id: str,
                   storage: WorkflowStorage) -> Any:
    """Driver-side event loop: submit dependency-ready steps, checkpoint
    results as they land, finish when the terminal step completes."""
    done = {sid for sid in state.steps
            if storage.has_step(workflow_id, sid)}
    # Load only checkpoints some remaining step (or the output) consumes —
    # resuming a mostly-done workflow shouldn't deserialize every
    # intermediate result.
    needed = {state.output_step}
    for sid, spec in state.steps.items():
        if sid not in done:
            needed.update(spec.dependencies())
    results: Dict[str, Any] = {
        sid: storage.load_step_result(workflow_id, sid)
        for sid in done & needed}

    def get_result(sid: str):
        """Step result, loading the checkpoint lazily on first use
        (adopted continuation sub-steps and resumed steps only pay
        deserialization when a consumer actually needs them)."""
        if sid not in results:
            results[sid] = storage.load_step_result(workflow_id, sid)
        return results[sid]

    def substitute(v):
        if isinstance(v, _StepRef):
            return get_result(v.step_id)
        if isinstance(v, _InputRef):
            return _project_input(v, state.input_args, state.input_kwargs)
        if isinstance(v, list):
            return [substitute(x) for x in v]
        if isinstance(v, tuple):
            return tuple(substitute(x) for x in v)
        if isinstance(v, dict):
            return {k: substitute(x) for k, x in v.items()}
        return v

    pending: Dict[Any, str] = {}  # ObjectRef -> step_id
    # parent step -> sub-output step it expanded into (continuation)
    aliases: Dict[str, str] = {}
    expanded: set = set()

    run_step = ray_tpu.remote(_run_step)

    def land(sid: str, value: Any):
        """A step produced a CONCRETE value: checkpoint it and cascade
        through any continuation parents aliased to it."""
        while True:
            storage.save_step_result(workflow_id, sid, value)
            results[sid] = value
            done.add(sid)
            parent = next((p for p, s in aliases.items() if s == sid),
                          None)
            if parent is None:
                return
            del aliases[parent]
            sid = parent

    def handle_result(sid: str, value: Any) -> None:
        if isinstance(value, _Continuation):
            # The step expands instead of completing: merge the
            # sub-DAG and alias this step to its output. Nothing is
            # checkpointed for the parent yet — a resume re-runs it
            # and re-expands to the SAME sub-step ids, picking up
            # whatever sub-steps already checkpointed.
            sub_out, new_ids = _expand_continuation(state, sid, value)
            aliases[sid] = sub_out
            expanded.add(sid)
            # A resumed run re-expands over sub-steps that already
            # checkpointed: adopt them (results load lazily on
            # first use, same pruning stance as the resume path).
            for nsid in new_ids:
                if storage.has_step(workflow_id, nsid):
                    done.add(nsid)
            if sub_out in done:
                del aliases[sid]
                land(sid, get_result(sub_out))
            return
        land(sid, value)

    def check_cancel():
        if storage.get_status(workflow_id) == WorkflowStatus.CANCELED:
            # Best-effort cancel of in-flight steps; completed ones
            # stay checkpointed, so a later resume() continues from
            # here (the canceled workflow is resumable by design).
            for ref in list(pending):
                try:
                    ray_tpu.cancel(ref)
                except Exception:
                    pass
            raise WorkflowCancelledError(workflow_id)

    def ready_steps():
        for sid, spec in list(state.steps.items()):
            if sid in done or sid in expanded or \
                    sid in pending.values():
                continue
            if all(d in done for d in spec.dependencies()):
                yield sid, spec

    def drain_pending():
        """Checkpoint every in-flight step that still completes, so a
        sibling failure doesn't discard finished work on resume."""
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1)
            sid = pending.pop(ready[0])
            try:
                value = ray_tpu.get(ready[0])
            except Exception:
                continue
            if isinstance(value, _Continuation):
                continue       # re-expanded by the resume's re-run
            storage.save_step_result(workflow_id, sid, value)

    while True:
        check_cancel()
        # Output-list steps complete synchronously and can unlock further
        # steps, so re-scan until the ready set is exhausted.
        progressed = True
        while progressed:
            progressed = False
            for sid, spec in list(ready_steps()):
                if spec.is_output_list:
                    land(sid, substitute(spec.args[0]))
                    progressed = True
                    continue
                args = substitute(spec.args)
                kwargs = substitute(spec.kwargs)
                fn = run_step
                opts = {k: v for k, v in spec.options.items()
                        if k in ("num_cpus", "num_tpus", "resources",
                                 "max_retries", "name")}
                if opts:
                    fn = fn.options(**opts)
                pending[fn.remote(spec.func, args, kwargs)] = sid
        if state.output_step in done:
            break
        if not pending:
            raise RuntimeError(
                f"workflow {workflow_id}: no runnable steps but output "
                f"not produced (cyclic or corrupt state)")
        # Bounded wait so a cancel() is observed within ~1s even while
        # a long step runs.
        ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                timeout=1.0)
        if not ready:
            continue
        ref = ready[0]
        sid = pending.pop(ref)
        try:
            value = ray_tpu.get(ref)  # raises on step failure
        except BaseException:
            drain_pending()
            raise
        handle_result(sid, value)

    return results[state.output_step]


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def run(dag: DAGNode, *args, workflow_id: Optional[str] = None,
        **kwargs) -> Any:
    """Execute a DAG durably; blocks and returns the final result."""
    storage = _get_storage()
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
    if storage.exists(workflow_id):
        status = storage.get_status(workflow_id)
        if status == WorkflowStatus.SUCCESSFUL:
            return storage.load_output(workflow_id)
        # A fresh DAG has fresh step ids; overwriting the stored graph
        # would orphan every prior checkpoint. Force an explicit choice.
        raise ValueError(
            f"workflow {workflow_id!r} already exists with status "
            f"{status}; call workflow.resume({workflow_id!r}) to continue "
            f"it or workflow.delete({workflow_id!r}) to start over")
    state = _state_from_dag(dag, args, kwargs)
    storage.save_state(workflow_id, state)
    storage.set_status(workflow_id, WorkflowStatus.RUNNING)
    try:
        out = _execute_state(state, workflow_id, storage)
    except WorkflowCancelledError:
        raise           # status already CANCELED; don't mark FAILED
    except BaseException:
        storage.set_status(workflow_id, WorkflowStatus.FAILED)
        raise
    storage.save_output(workflow_id, out)
    storage.set_status(workflow_id, WorkflowStatus.SUCCESSFUL)
    return out


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None,
              **kwargs):
    """Execute a DAG durably in the background; returns an ObjectRef."""
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
    storage = _get_storage()
    if storage.exists(workflow_id):
        status = storage.get_status(workflow_id)
        if status == WorkflowStatus.SUCCESSFUL:
            return ray_tpu.put(storage.load_output(workflow_id))
        raise ValueError(
            f"workflow {workflow_id!r} already exists with status "
            f"{status}; resume() or delete() it first")
    storage_base = storage.base

    # Driver loop runs inside a detached task so the caller is free.
    def _drive(base, wf_id, dag_state):
        import ray_tpu.workflow as wf
        wf.init(base)
        st = wf._get_storage()
        try:
            out = wf._execute_state(dag_state, wf_id, st)
        except wf.WorkflowCancelledError:
            raise       # status already CANCELED; don't mark FAILED
        except BaseException:
            st.set_status(wf_id, WorkflowStatus.FAILED)
            raise
        st.save_output(wf_id, out)
        st.set_status(wf_id, WorkflowStatus.SUCCESSFUL)
        return out

    state = _state_from_dag(dag, args, kwargs)
    # Persist state + RUNNING before returning so get_status/get_output
    # polled immediately after run_async see an in-flight workflow.
    storage.save_state(workflow_id, state)
    storage.set_status(workflow_id, WorkflowStatus.RUNNING)
    return ray_tpu.remote(_drive).remote(storage_base, workflow_id, state)


def resume(workflow_id: str) -> Any:
    """Resume a failed/interrupted workflow; completed steps are skipped."""
    storage = _get_storage()
    if not storage.exists(workflow_id):
        raise ValueError(f"no such workflow: {workflow_id}")
    if storage.get_status(workflow_id) == WorkflowStatus.SUCCESSFUL:
        return storage.load_output(workflow_id)
    state = storage.load_state(workflow_id)
    storage.set_status(workflow_id, WorkflowStatus.RUNNING)
    try:
        out = _execute_state(state, workflow_id, storage)
    except WorkflowCancelledError:
        raise           # status already CANCELED; don't mark FAILED
    except BaseException:
        storage.set_status(workflow_id, WorkflowStatus.FAILED)
        raise
    storage.save_output(workflow_id, out)
    storage.set_status(workflow_id, WorkflowStatus.SUCCESSFUL)
    return out


def resume_all() -> List[Tuple[str, Any]]:
    """Resume every FAILED/RESUMABLE stored workflow; returns
    (workflow_id, result) pairs for the ones that succeed. RUNNING
    workflows are skipped — they may be live under run_async, and
    resuming one would double-execute its steps."""
    storage = _get_storage()
    out = []
    for wf_id in storage.list_workflows():
        if storage.get_status(wf_id) in (WorkflowStatus.FAILED,
                                         WorkflowStatus.RESUMABLE):
            try:
                out.append((wf_id, resume(wf_id)))
            except Exception:
                pass
    return out


def cancel(workflow_id: str) -> None:
    """Request cancellation (reference: workflow.cancel): the driving
    loop observes the CANCELED status at its next scheduling point,
    cancels in-flight steps best-effort, and raises
    WorkflowCancelledError to its caller. Checkpointed step results
    are KEPT — resume(workflow_id) continues the workflow later."""
    storage = _get_storage()
    if not storage.exists(workflow_id):
        raise ValueError(f"no workflow {workflow_id!r}")
    if storage.get_status(workflow_id) == WorkflowStatus.SUCCESSFUL:
        return     # completed first: cancellation lost the race
    storage.set_status(workflow_id, WorkflowStatus.CANCELED)


def get_status(workflow_id: str) -> Optional[str]:
    return _get_storage().get_status(workflow_id)


def get_output(workflow_id: str, timeout: Optional[float] = None) -> Any:
    """Fetch the stored output of a workflow, waiting if it is RUNNING."""
    storage = _get_storage()
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        if storage.has_output(workflow_id):
            return storage.load_output(workflow_id)
        status = storage.get_status(workflow_id)
        if status in (WorkflowStatus.FAILED,
                      WorkflowStatus.CANCELED, None):
            raise RuntimeError(
                f"workflow {workflow_id} has no output (status={status})")
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(f"workflow {workflow_id} still {status}")
        time.sleep(0.05)


def list_all() -> List[Tuple[str, Optional[str]]]:
    storage = _get_storage()
    return [(wf, storage.get_status(wf))
            for wf in storage.list_workflows()]


def delete(workflow_id: str) -> None:
    _get_storage().delete(workflow_id)
