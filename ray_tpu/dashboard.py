"""Dashboard: HTTP observability endpoints.

Capability parity with the reference's dashboard head (dashboard/head.py
+ modules): JSON endpoints for cluster summary, actors, tasks, objects,
workers, the chrome-trace timeline, Prometheus metrics exposition
(python/ray/_private/metrics_agent.py role), and a dependency-free HTML
frontend at "/" (dashboard/client role, dashboard_ui.py).
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._thread: Optional[threading.Thread] = None

    async def _index(self, request):
        from aiohttp import web
        from ray_tpu.dashboard_ui import INDEX_HTML
        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _summary(self, request):
        from aiohttp import web
        from ray_tpu import state
        return web.json_response(state.cluster_summary())

    async def _actors(self, request):
        from aiohttp import web
        from ray_tpu import state
        return web.json_response(state.list_actors())

    async def _tasks(self, request):
        from aiohttp import web
        from ray_tpu import state
        return web.json_response(state.list_tasks())

    async def _objects(self, request):
        from aiohttp import web
        from ray_tpu import state
        return web.json_response(state.list_objects())

    async def _workers(self, request):
        from aiohttp import web
        from ray_tpu import state
        return web.json_response(state.list_workers())

    async def _nodes(self, request):
        from aiohttp import web
        from ray_tpu import state
        return web.json_response(state.list_nodes())

    async def _timeline(self, request):
        from aiohttp import web
        from ray_tpu._private import profiling
        return web.json_response(profiling.chrome_trace())

    async def _metrics(self, request):
        from aiohttp import web
        from ray_tpu.util.metrics import prometheus_text
        return web.Response(text=prometheus_text(),
                            content_type="text/plain")

    async def _serve_status(self, request):
        """Serve application view: deployment statuses plus live
        per-replica stats (ongoing/total and any serve_stats() user
        metrics, e.g. LLM engine slot occupancy). Empty when no serve
        controller is running."""
        from aiohttp import web
        loop = asyncio.get_event_loop()

        def collect():
            import ray_tpu
            from ray_tpu.serve import api as serve_api
            try:
                controller = ray_tpu.get_actor(
                    serve_api.CONTROLLER_NAME)
            except Exception:
                return {"deployments": {}}
            # Strictly read-only: use the handle we already resolved
            # (serve.status()/list_deployments() would re-create a
            # controller that a racing shutdown just killed).
            try:
                info = ray_tpu.get(
                    controller.list_deployments.remote(), timeout=2)
            except Exception:
                return {"deployments": {}}
            out = {"deployments": {}}
            for name, d in info.items():
                d = dict(d)
                d["status"] = ("HEALTHY"
                               if d["num_replicas"] >= max(
                                   1, d["target"])
                               else "UPDATING")
                d["replica_stats"] = []
                out["deployments"][name] = d
            # Batch: all replica-stats refs first, ONE bounded get —
            # a hung replica costs one timeout, not 2s x replicas.
            pending = []     # (name, rid, ref)
            for name in info:
                try:
                    reps = ray_tpu.get(
                        controller.get_replicas.remote(name),
                        timeout=2)
                    for rid, h in reps["replicas"]:
                        pending.append((name, rid, h.stats.remote()))
                except Exception:
                    pass
            if pending:
                try:
                    vals = ray_tpu.get([r for _, _, r in pending],
                                       timeout=2)
                except Exception as e:
                    vals = [{"replica_id": rid, "error": repr(e)}
                            for _, rid, _ in pending]
                for (name, rid, _), stats in zip(pending, vals):
                    out["deployments"][name]["replica_stats"].append(
                        stats)
            return out

        return web.json_response(
            await loop.run_in_executor(None, collect))

    def _run(self):
        from aiohttp import web
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/cluster_summary", self._summary)
        app.router.add_get("/api/actors", self._actors)
        app.router.add_get("/api/tasks", self._tasks)
        app.router.add_get("/api/objects", self._objects)
        app.router.add_get("/api/workers", self._workers)
        app.router.add_get("/api/nodes", self._nodes)
        app.router.add_get("/api/timeline", self._timeline)
        app.router.add_get("/api/serve", self._serve_status)
        app.router.add_get("/metrics", self._metrics)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        if runner.addresses:
            self.port = runner.addresses[0][1]
        self._started.set()
        loop.run_forever()

    def start(self, timeout: float = 10.0) -> "Dashboard":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dashboard")
        self._thread.start()
        if not self._started.wait(timeout):
            raise TimeoutError("dashboard failed to start")
        return self

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
