"""Public task/actor API.

Capability parity with the reference's public surface:
``ray.remote/get/put/wait/kill/cancel/get_actor`` +
``RemoteFunction``/``ActorClass``/``ActorHandle`` with ``.options(...)``
chaining (python/ray/_private/worker.py:2681, python/ray/remote_function.py:35,
python/ray/actor.py:377,1020). Fresh implementation over the pluggable
runtime.
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, List, Optional, Union

from ray_tpu._private.ids import ActorID, ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.task_spec import (ActorCreationSpec, TaskSpec,
                                        resources_from_options,
                                        validate_actor_options,
                                        validate_task_options)
from ray_tpu._private.worker import global_worker
from ray_tpu._private.config import GlobalConfig


# --------------------------------------------------------------------------
# Object API
# --------------------------------------------------------------------------

def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed")
    return global_worker().runtime.put(value)


def get(refs: Union[ObjectRef, List[ObjectRef]],
        timeout: Optional[float] = None) -> Any:
    return global_worker().runtime.get(refs, timeout=timeout)


def wait(refs: List[ObjectRef], num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return global_worker().runtime.wait(refs, num_returns=num_returns,
                                        timeout=timeout)


def cancel(ref: ObjectRef, force: bool = False, recursive: bool = True):
    return global_worker().runtime.cancel(ref, force=force,
                                          recursive=recursive)


# --------------------------------------------------------------------------
# Tasks
# --------------------------------------------------------------------------

def _maybe_trace(spec_name: str, kind: str):
    """Client-side invocation span + shipped context (no-op unless
    tracing was enabled via ray_tpu.util.tracing.setup_tracing)."""
    from ray_tpu.util import tracing
    return tracing.invocation_context(spec_name, kind)


class RemoteFunction:
    def __init__(self, func, options: Dict[str, Any]):
        self._func = func
        self._options = validate_task_options(options)
        # Submission-invariant fields, resolved once: .remote() is the
        # framework's hottest call site.
        self._name = self._options["name"] or getattr(
            func, "__qualname__", "anonymous")
        self._resources = resources_from_options(self._options)
        functools.update_wrapper(self, func)

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        return RemoteFunction(self._func, merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        """Lazy call graph node (reference: python/ray/dag)."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    def _remote(self, args, kwargs, opts):
        w = global_worker()
        rt = w.runtime
        num_returns = opts["num_returns"]
        n = 1 if num_returns == "streaming" else num_returns
        task_id = TaskID.of(rt.job_id)
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(n)]
        max_retries = opts["max_retries"]
        if max_retries is None:
            max_retries = GlobalConfig.default_max_retries
        if opts is self._options:
            name, resources = self._name, self._resources
        else:   # .options(...) overrides: resolve per call
            name = opts["name"] or getattr(self._func, "__qualname__",
                                           "anonymous")
            resources = resources_from_options(opts)
        from ray_tpu.util import tracing
        spec = TaskSpec(
            task_id=task_id,
            job_id=rt.job_id,
            name=name,
            func=self._func,
            args=tuple(args),
            kwargs=dict(kwargs),
            num_returns=n,
            return_ids=return_ids,
            resources=resources,
            max_retries=max_retries,
            retry_exceptions=opts["retry_exceptions"],
            scheduling_strategy=opts["scheduling_strategy"],
            runtime_env=opts["runtime_env"],
            trace_ctx=(None if not tracing._enabled else
                       _maybe_trace(spec_name=name, kind="task")),
        )
        refs = rt.submit_task(spec)
        if num_returns == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self.__name__!r} cannot be called directly; "
            f"use .remote()")


# --------------------------------------------------------------------------
# Actors
# --------------------------------------------------------------------------

class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, num_returns: Optional[int] = None,
                concurrency_group: Optional[str] = None):
        return ActorMethod(
            self._handle, self._method_name,
            self._num_returns if num_returns is None else num_returns,
            self._concurrency_group if concurrency_group is None
            else concurrency_group)

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs, self._num_returns,
            self._concurrency_group)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called "
            f"directly; use .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, cls: type,
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._cls = cls
        self._max_task_retries = max_task_retries

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        attr = getattr(self._cls, name, None)
        if attr is None or not callable(attr):
            raise AttributeError(
                f"{self._cls.__name__} has no method {name!r}")
        method_opts = getattr(attr, "__ray_tpu_method_opts__", {})
        return ActorMethod(
            self, name,
            num_returns=method_opts.get("num_returns", 1),
            concurrency_group=method_opts.get("concurrency_group"))

    def _submit_method(self, method_name, args, kwargs, num_returns,
                       concurrency_group=None):
        w = global_worker()
        rt = w.runtime
        task_id = TaskID.of(rt.job_id)
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(max(1, num_returns))]
        spec = TaskSpec(
            task_id=task_id,
            job_id=rt.job_id,
            name=f"{self._cls.__name__}.{method_name}",
            func=None,
            args=tuple(args),
            kwargs=dict(kwargs),
            num_returns=num_returns,
            return_ids=return_ids,
            resources={},
            max_retries=self._max_task_retries,
            actor_id=self._actor_id,
            method_name=method_name,
            concurrency_group=concurrency_group,
            trace_ctx=_maybe_trace(
                f"{self._cls.__name__}.{method_name}", "actor_task"),
        )
        refs = rt.submit_actor_task(self._actor_id, spec)
        if num_returns == 1:
            return refs[0]
        return refs

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id.binary(), self._cls,
                                  self._max_task_retries))

    def __repr__(self):
        return (f"ActorHandle({self._cls.__name__}, "
                f"{self._actor_id.hex()[:12]})")


def _rebuild_handle(actor_id_bin, cls, max_task_retries):
    return ActorHandle(ActorID(actor_id_bin), cls, max_task_retries)


class ActorClass:
    def __init__(self, cls: type, options: Dict[str, Any]):
        self._cls = cls
        self._options = validate_actor_options(options)
        functools.update_wrapper(self, cls, updated=[])

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        opts = self._options
        w = global_worker()
        rt = w.runtime
        if opts["get_if_exists"] and opts["name"]:
            try:
                return get_actor(opts["name"], opts["namespace"])
            except ValueError:
                pass
        is_async = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(self._cls,
                                           inspect.isfunction))
        max_concurrency = opts["max_concurrency"]
        if max_concurrency is None:
            max_concurrency = 1000 if is_async else 1
        spec = ActorCreationSpec(
            actor_id=ActorID.of(rt.job_id),
            job_id=rt.job_id,
            cls=self._cls,
            args=args,
            kwargs=kwargs,
            resources=resources_from_options(opts),
            max_restarts=opts["max_restarts"],
            max_task_retries=opts["max_task_retries"],
            max_concurrency=max_concurrency,
            max_pending_calls=opts["max_pending_calls"],
            name=opts["name"],
            namespace=opts["namespace"] or w.namespace,
            lifetime=opts["lifetime"],
            scheduling_strategy=opts["scheduling_strategy"],
            runtime_env=opts["runtime_env"],
            concurrency_groups=opts["concurrency_groups"],
            is_async=is_async,
            get_if_exists=bool(opts["get_if_exists"] and opts["name"]),
        )
        state = rt.create_actor(spec)
        # With get_if_exists a concurrent creator may have won the name
        # race: the returned state is authoritative, not our spec.
        actor_id = state.spec.actor_id
        handle = ActorHandle(actor_id, self._cls,
                             opts["max_task_retries"])
        rt._actor_handles[actor_id] = handle
        return handle

    def bind(self, *args, **kwargs):
        """Lazy actor-graph node (reference: python/ray/dag/class_node.py)."""
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated "
            f"directly; use .remote()")


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    w = global_worker()
    rt = w.runtime
    actor_id = rt.lookup_named_actor(name, namespace or w.namespace)
    handle = rt._actor_handles.get(actor_id)
    if handle is None:
        st = rt.get_actor_state(actor_id)
        handle = ActorHandle(actor_id, st.spec.cls,
                             st.spec.max_task_retries)
    return handle


def kill(actor: ActorHandle, no_restart: bool = True):
    global_worker().runtime.kill_actor(actor.actor_id,
                                       no_restart=no_restart)


# --------------------------------------------------------------------------
# The decorator
# --------------------------------------------------------------------------

def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=..., ...)`` for
    functions and classes."""
    if len(args) == 1 and not kwargs and (inspect.isfunction(args[0]) or
                                          inspect.isclass(args[0])):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target, {})
        return RemoteFunction(target, {})
    if args:
        raise TypeError("@remote takes only keyword options")

    def wrapper(target):
        if inspect.isclass(target):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)
    return wrapper


# --------------------------------------------------------------------------
# Cluster introspection
# --------------------------------------------------------------------------

def cluster_resources() -> Dict[str, float]:
    return global_worker().runtime.cluster_resources()


def available_resources() -> Dict[str, float]:
    return global_worker().runtime.available_resources()


def nodes():
    """Cluster node table (reference: ray.nodes() — the same rows the
    state API's list_nodes serves)."""
    from ray_tpu.state import list_nodes
    return list_nodes()


def timeline(filename: Optional[str] = None):
    from ray_tpu._private import profiling
    return profiling.chrome_trace(filename)
