"""Exception hierarchy for ray_tpu.

Capability parity with the reference's ``python/ray/exceptions.py`` (RayError,
RayTaskError, RayActorError, ObjectLostError, GetTimeoutError, ...), designed
fresh for this runtime.
"""
from __future__ import annotations

import traceback as _tb


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task.

    Re-raised on ``get()`` at the caller, carrying the remote traceback
    (reference: RayTaskError in python/ray/exceptions.py).
    """

    def __init__(self, cause: BaseException, task_name: str = "",
                 remote_traceback: str | None = None):
        self.cause = cause
        self.task_name = task_name
        self.remote_traceback = remote_traceback or "".join(
            _tb.format_exception(type(cause), cause, cause.__traceback__))
        super().__init__(str(cause))

    def __str__(self):
        return (f"Task '{self.task_name}' failed with "
                f"{type(self.cause).__name__}: {self.cause}\n"
                f"--- remote traceback ---\n{self.remote_traceback}")

    def __reduce__(self):
        # Exceptions pickle via (cls, self.args) by default, which would
        # pass the message string as `cause`; preserve the real fields
        # (these cross process boundaries in the distributed runtime).
        return (type(self), (self.cause, self.task_name,
                             self.remote_traceback))


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    """The actor is dead (killed, crashed in __init__, or out of restarts)."""

    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} is dead: {reason}")

    def __reduce__(self):
        return (type(self), (self.actor_id, self.reason))


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """An object is no longer available and cannot be reconstructed."""

    def __init__(self, object_id=None, reason: str = "object lost"):
        self.object_id = object_id
        self.reason = reason
        super().__init__(f"Object {object_id} lost: {reason}")

    def __reduce__(self):
        return (type(self), (self.object_id, self.reason))


class OwnerDiedError(ObjectLostError):
    """The owner process of an object died, so the object is unrecoverable."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(ref, timeout=...)`` timed out."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before or during execution."""

    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")

    def __reduce__(self):
        return (type(self), (self.task_id,))


class PendingCallsLimitExceeded(RayTpuError):
    """Actor's pending call queue is over ``max_pending_calls``."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the runtime environment for a task/actor."""


class NodeDiedError(RayTpuError):
    """A node (host) in the cluster died."""


class PlacementGroupError(RayTpuError):
    """Placement group creation/scheduling failed."""


class MeshGangError(RayTpuError):
    """A member of an SPMD mesh gang failed; the whole gang must recover
    together (gang semantics, see SURVEY.md §7 design stance)."""

    def __init__(self, gang_id=None, failed_member=None, reason: str = ""):
        self.gang_id = gang_id
        self.failed_member = failed_member
        super().__init__(
            f"Mesh gang {gang_id} failed (member={failed_member}): {reason}")
