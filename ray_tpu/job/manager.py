"""Server-side job manager hosted by the head.

Reference: dashboard/modules/job/job_manager.py — there the driver runs
under a supervisor actor; here the head spawns the entrypoint as a child
process with RAY_TPU_ADDRESS injected, which is the same shape without a
dashboard middleman.
"""
from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class JobInfo:
    def __init__(self, job_id: str, entrypoint: str,
                 metadata: Optional[Dict[str, str]] = None):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.metadata = metadata or {}
        self.status = JobStatus.PENDING
        self.message = ""
        self.start_time = time.time()
        self.end_time: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"job_id": self.job_id, "entrypoint": self.entrypoint,
                "status": self.status, "message": self.message,
                "metadata": dict(self.metadata),
                "start_time": self.start_time,
                "end_time": self.end_time}


class JobManager:
    def __init__(self, head_address: str, log_dir: Optional[str] = None):
        self._head_address = head_address
        self._log_dir = log_dir or os.path.join(
            "/tmp", "ray_tpu", f"session_{os.getpid()}", "logs")
        os.makedirs(self._log_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}

    def log_path(self, job_id: str) -> str:
        return os.path.join(self._log_dir, f"job-{job_id}.log")

    def submit_job(self, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"Job {job_id!r} already exists")
            info = JobInfo(job_id, entrypoint, metadata)
            self._jobs[job_id] = info
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)   # breaks the TPU plugin discovery
        env["RAY_TPU_ADDRESS"] = self._head_address
        env["RAY_TPU_JOB_ID"] = job_id
        cwd = None
        runtime_env = runtime_env or {}
        if runtime_env.get("working_dir"):
            cwd = runtime_env["working_dir"]
        for k, v in (runtime_env.get("env_vars") or {}).items():
            env[k] = str(v)
        log_f = open(self.log_path(job_id), "wb")
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, cwd=cwd, env=env,
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True)
        except OSError as e:
            log_f.close()
            with self._lock:
                info.status = JobStatus.FAILED
                info.message = str(e)
                info.end_time = time.time()
            return job_id
        with self._lock:
            info.status = JobStatus.RUNNING
            self._procs[job_id] = proc
        threading.Thread(target=self._wait_job, args=(job_id, proc, log_f),
                         daemon=True, name=f"job-wait-{job_id}").start()
        return job_id

    def _wait_job(self, job_id: str, proc: subprocess.Popen, log_f):
        rc = proc.wait()
        log_f.close()
        with self._lock:
            info = self._jobs[job_id]
            if info.status == JobStatus.STOPPED:
                pass
            elif rc == 0:
                info.status = JobStatus.SUCCEEDED
            else:
                info.status = JobStatus.FAILED
                info.message = f"exit code {rc}"
            info.end_time = time.time()
            self._procs.pop(job_id, None)

    def stop_job(self, job_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(job_id)
            proc = self._procs.get(job_id)
            if info is None:
                raise ValueError(f"No job {job_id!r}")
            if info.status in JobStatus.TERMINAL:
                return False
            info.status = JobStatus.STOPPED
            info.end_time = time.time()
        if proc is not None:
            try:
                proc.terminate()
                try:
                    proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    proc.kill()
            except OSError:
                pass
        return True

    def get_job_status(self, job_id: str) -> str:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"No job {job_id!r}")
            return info.status

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"No job {job_id!r}")
            return info.to_dict()

    def get_job_logs(self, job_id: str) -> str:
        path = self.log_path(job_id)
        if not os.path.exists(path):
            return ""
        with open(path, "rb") as f:
            return f.read().decode(errors="replace")

    def list_jobs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [i.to_dict() for i in self._jobs.values()]

    def shutdown(self):
        with self._lock:
            job_ids = [jid for jid, i in self._jobs.items()
                       if i.status not in JobStatus.TERMINAL]
        for jid in job_ids:
            try:
                self.stop_job(jid)
            except ValueError:
                pass
