"""Client SDK for job submission.

Reference: dashboard/modules/job/sdk.py:34,83 (JobSubmissionClient) —
REST there, head RPC here; identical surface: submit/stop/status/logs/
list + wait helper.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu.job.manager import JobStatus
from ray_tpu.runtime.rpc import RpcClient


class JobSubmissionClient:
    def __init__(self, address: str):
        self._client = RpcClient(address, timeout=30)

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        return self._client.call("submit_job", entrypoint,
                                 submission_id, runtime_env, metadata)

    def stop_job(self, job_id: str) -> bool:
        return self._client.call("stop_job", job_id)

    def get_job_status(self, job_id: str) -> str:
        return self._client.call("get_job_status", job_id)

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        return self._client.call("get_job_info", job_id)

    def get_job_logs(self, job_id: str) -> str:
        return self._client.call("get_job_logs", job_id)

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._client.call("list_jobs")

    def wait_until_finished(self, job_id: str,
                            timeout: float = 300) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(job_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.2)
        raise TimeoutError(
            f"Job {job_id} not finished within {timeout}s")
