"""Job submission: run driver scripts against a cluster.

Capability parity with the reference's job submission stack
(dashboard/modules/job/ — JobSubmissionClient.submit_job sdk.py:34,83,
server-side job_manager.py supervising the entrypoint process): jobs are
entrypoint commands spawned by the head with the cluster address in
their environment, tracked through a PENDING/RUNNING/SUCCEEDED/FAILED/
STOPPED lifecycle with captured logs.
"""
from ray_tpu.job.manager import JobInfo, JobManager, JobStatus
from ray_tpu.job.sdk import JobSubmissionClient

__all__ = ["JobManager", "JobInfo", "JobStatus", "JobSubmissionClient"]
