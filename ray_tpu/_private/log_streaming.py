"""Worker log capture + driver streaming (log_to_driver).

Capability parity with the reference's log pipeline: workers redirect
stdout/stderr, records flow to the driver tagged with their origin
(python/ray/_private/log_monitor.py:100 tails files and publishes over
GCS pub/sub; ray_logging formats "(name pid=...)" prefixes). TPU-first
delta: capture happens in-process (no file tailing) and records ride
the head's stream pub/sub channel in batches.
"""
from __future__ import annotations

import io
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

LOG_CHANNEL = "logs"

# Thread-local execution tag ("task:<name>" / "actor:<id>") set by the
# executor around user code so captured lines carry their origin.
_log_ctx = threading.local()


def set_log_tag(tag: Optional[str]):
    _log_ctx.tag = tag


def get_log_tag() -> Optional[str]:
    return getattr(_log_ctx, "tag", None)


class _TeeStream(io.TextIOBase):
    """Replaces a worker's stdout/stderr: passes writes through to the
    original stream AND queues complete lines for batched publishing."""

    def __init__(self, orig, stream_name: str, collector):
        self._orig = orig
        self._name = stream_name
        self._collector = collector
        self._buf = ""
        # The executor runs tasks on a thread pool and this object is
        # the process-wide sys.stdout: the buffer read-modify-write
        # must be serialized or concurrent prints lose/mangle lines.
        self._wlock = threading.Lock()

    def write(self, s: str) -> int:
        try:
            self._orig.write(s)
        except Exception:
            pass
        lines = []
        with self._wlock:
            self._buf += s
            while "\n" in self._buf:
                line, self._buf = self._buf.split("\n", 1)
                if line:
                    lines.append(line)
        for line in lines:
            self._collector(self._name, line)
        return len(s)

    def flush(self):
        try:
            self._orig.flush()
        except Exception:
            pass

    # Keep common file-object API working for user code.
    def isatty(self):
        return False

    @property
    def encoding(self):
        return getattr(self._orig, "encoding", "utf-8")

    def fileno(self):
        return self._orig.fileno()


class WorkerLogPublisher:
    """Installs stdout/stderr capture in a worker process and ships
    line batches to the head's `logs` stream channel."""

    def __init__(self, head_client, worker_id: str,
                 flush_interval: float = 0.1, max_batch: int = 200):
        self.head = head_client
        self.worker_id = worker_id
        self.flush_interval = flush_interval
        self.max_batch = max_batch
        self._pending: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def install(self):
        sys.stdout = _TeeStream(sys.stdout, "out", self._collect)
        sys.stderr = _TeeStream(sys.stderr, "err", self._collect)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="log-publisher")
        self._thread.start()

    def _collect(self, stream: str, line: str):
        rec = {"worker_id": self.worker_id, "pid": os.getpid(),
               "stream": stream, "line": line,
               "tag": get_log_tag(), "ts": time.time()}
        with self._lock:
            self._pending.append(rec)
            if len(self._pending) > 10000:     # runaway printer guard
                del self._pending[:5000]
        self._wake.set()

    def _loop(self):
        while not self._stop.is_set():
            self._wake.wait(timeout=1.0)
            self._wake.clear()
            time.sleep(self.flush_interval)
            with self._lock:
                batch, self._pending = \
                    self._pending[:self.max_batch], \
                    self._pending[self.max_batch:]
            if batch:
                try:
                    self.head.call_oneway("publish", LOG_CHANNEL, batch,
                                          stream=True, fast=True)
                except Exception:
                    pass       # head gone; drop rather than block user
            with self._lock:
                if self._pending:
                    self._wake.set()

    def stop(self):
        self._stop.set()


def default_sink(rec: Dict[str, Any]):
    tag = rec.get("tag") or rec["worker_id"]
    stream = sys.stderr if rec["stream"] == "err" else sys.stdout
    print(f"({tag} pid={rec['pid']}) {rec['line']}", file=stream)


class DriverLogStreamer:
    """Driver side of log_to_driver: subscribes to the `logs` stream
    and forwards each record to a sink (print, by default)."""

    def __init__(self, head_addr: str,
                 sink: Optional[Callable] = None):
        from ray_tpu.runtime.pubsub import Subscriber
        from ray_tpu.runtime.rpc import RpcClient
        self.sinks: List[Callable] = [sink or default_sink]
        client = RpcClient(head_addr)
        # Attach at the live edge: don't replay the hub's retained
        # history (another job's logs) into a freshly attached driver.
        try:
            from_seq = client.call("psub_stream_seq", LOG_CHANNEL,
                                   timeout=5)
        except Exception:
            from_seq = 0
        self._sub = Subscriber(client)
        self._sub.subscribe_stream(LOG_CHANNEL, self._on_batch,
                                   from_seq=from_seq)

    def add_sink(self, sink: Callable):
        self.sinks.append(sink)

    def _on_batch(self, seq: int, batch):
        for rec in batch:
            for sink in self.sinks:
                try:
                    sink(rec)
                except Exception:
                    pass

    def stop(self):
        self._sub.stop()
