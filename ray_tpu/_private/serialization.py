"""Serialization layer.

Capability parity with the reference's python/ray/_private/serialization.py:
cloudpickle for closures/classes, zero-copy handling of large numpy arrays,
and capture of ObjectRef instances inside serialized values so the ownership
layer can register borrowers.
"""
from __future__ import annotations

import io
import pickle
from typing import Any, List, Tuple

import cloudpickle
import numpy as np

# Arrays above this many bytes are serialized out-of-band (zero-copy buffers)
_OOB_THRESHOLD = 1 << 16


class SerializedObject:
    """A serialized value: a pickle stream plus out-of-band buffers and the
    ObjectRefs it captured (for borrower registration)."""

    __slots__ = ("data", "buffers", "contained_refs")

    def __init__(self, data: bytes, buffers: List[pickle.PickleBuffer],
                 contained_refs: List[Any]):
        self.data = data
        self.buffers = buffers
        self.contained_refs = contained_refs

    def total_bytes(self) -> int:
        n = len(self.data)
        for b in self.buffers:
            n += b.raw().nbytes
        return n


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)
        self.contained_refs: List[Any] = []

    def persistent_id(self, obj):
        # Lazy import to avoid a cycle at module load.
        from ray_tpu._private.object_ref import ObjectRef, \
            _promote_if_local
        if isinstance(obj, ObjectRef):
            # The ref is escaping this process inside a serialized
            # value: its object must leave the memory tier for shm.
            _promote_if_local(obj.id)
            self.contained_refs.append(obj)
            return ("ray_tpu.ObjectRef", obj.id.binary(), obj.owner_hint)
        return None


class _Unpickler(pickle.Unpickler):
    def persistent_load(self, pid):
        tag = pid[0]
        if tag == "ray_tpu.ObjectRef":
            from ray_tpu._private.object_ref import ObjectRef
            from ray_tpu._private.ids import ObjectID
            return ObjectRef(ObjectID(pid[1]), owner_hint=pid[2],
                             _register_borrow=True)
        raise pickle.UnpicklingError(f"unknown persistent id {pid!r}")


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []

    def buffer_cb(buf: pickle.PickleBuffer):
        raw = buf.raw()
        if raw.nbytes >= _OOB_THRESHOLD:
            buffers.append(buf)
            return False  # keep out-of-band
        return True       # fold small buffers in-band

    f = io.BytesIO()
    p = _Pickler(f, buffer_cb)
    p.dump(value)
    return SerializedObject(f.getvalue(), buffers, p.contained_refs)


def deserialize(obj: SerializedObject) -> Any:
    return _Unpickler(io.BytesIO(obj.data),
                      buffers=obj.buffers).load()


def serialize_parts(value: Any):
    """Zero-copy framing: the flat-form layout of dumps() as a list of
    buffer-like parts (header bytes + pickle stream + raw OOB buffer
    views) plus the total byte length. Writers stream the parts
    straight into their destination (shm mapping, socket) — for a 1 GB
    array this is ONE memcpy instead of the three dumps() pays
    (tobytes + join + final copy)."""
    so = serialize(value)
    body = [so.data] + [b.raw() for b in so.buffers]
    header = (len(body).to_bytes(4, "little") +
              np.array([len(p) if isinstance(p, bytes) else p.nbytes
                        for p in body], dtype=np.int64).tobytes())
    parts = [header] + body
    total = sum(len(p) if isinstance(p, bytes) else p.nbytes
                for p in parts)
    return parts, total, so.contained_refs


def dumps(value: Any) -> bytes:
    """Flat single-buffer form (for IPC / the native store)."""
    parts, total, _ = serialize_parts(value)
    out = bytearray(total)
    off = 0
    for p in parts:
        n = len(p) if isinstance(p, bytes) else p.nbytes
        out[off:off + n] = p
        off += n
    return bytes(out)


_INTERNED: dict = {}


def _intern_blob(value: Any) -> bytes:
    """dumps() a constant once and remember the blob so loads() can
    short-circuit the unpickler for it (used for the ubiquitous
    ("ok", None) task result)."""
    blob = dumps(value)
    _INTERNED[blob] = value
    return blob


def loads(data) -> Any:
    """Deserialize a flat blob. Accepts bytes OR a memoryview — a
    pinned shm view deserializes ZERO-COPY: the out-of-band numpy
    buffers alias the mapping and keep the store pin alive through
    the buffer chain (see shm_store._PinnedExporter)."""
    if isinstance(data, bytes):
        if len(data) < 64 and data in _INTERNED:  # tiny constants only
            return _INTERNED[data]
        mv = memoryview(data)
    else:
        mv = data if isinstance(data, memoryview) else memoryview(data)
    nparts = int.from_bytes(mv[:4], "little")
    sizes = np.frombuffer(mv[4:4 + 8 * nparts], dtype=np.int64)
    off = 4 + 8 * nparts
    parts: List[memoryview] = []
    for s in sizes:
        parts.append(mv[off:off + int(s)])
        off += int(s)
    so = SerializedObject(bytes(parts[0]),
                          [pickle.PickleBuffer(p) for p in parts[1:]], [])
    return deserialize(so)


# Interned in EVERY process at import (the blob is deterministic), so a
# reader short-circuits regardless of which process wrote it.
NONE_RESULT_BLOB = _intern_blob(("ok", None))
