"""ctypes binding for the C++ shared-memory metrics core
(src/metrics/shm_metrics.cc — the native stats substrate, N20).

Worker processes record counters/gauges/histograms with lock-free
atomics into a shm segment created by the node; the head reads the
whole segment for aggregation/Prometheus export without any RPC on the
metrics hot path (reference: src/ray/stats/metric.h DEFINE_stats +
metric_exporter.cc, re-designed for one-host shm instead of the
opencensus-to-agent pipeline).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC = os.path.join(_REPO_ROOT, "src", "metrics", "shm_metrics.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LIB = os.path.join(_BUILD_DIR, "libshm_metrics.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

TYPE_COUNTER = 1
TYPE_GAUGE = 2
TYPE_HISTOGRAM = 3


def _ensure_built() -> str:
    if not os.path.exists(_LIB) or \
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-Wall", "-fPIC", "-std=c++17", "-shared",
             "-o", _LIB, _SRC, "-lpthread", "-lrt"],
            check=True, capture_output=True)
    return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_ensure_built())
        lib.metrics_create.restype = ctypes.c_void_p
        lib.metrics_create.argtypes = [ctypes.c_char_p]
        lib.metrics_attach.restype = ctypes.c_void_p
        lib.metrics_attach.argtypes = [ctypes.c_char_p]
        lib.metrics_detach.argtypes = [ctypes.c_void_p]
        lib.metrics_destroy.argtypes = [ctypes.c_void_p,
                                        ctypes.c_char_p]
        for fn in ("metrics_counter_add", "metrics_gauge_set",
                   "metrics_histogram_observe"):
            f = getattr(lib, fn)
            f.restype = ctypes.c_int
            f.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                          ctypes.c_double]
        lib.metrics_num_slots.restype = ctypes.c_int
        lib.metrics_num_slots.argtypes = [ctypes.c_void_p]
        lib.metrics_read_slot.restype = ctypes.c_int
        lib.metrics_read_slot.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.metrics_name_size.restype = ctypes.c_int
        lib.metrics_num_buckets.restype = ctypes.c_int
        _lib = lib
        return lib


class ShmMetricsRegistry:
    """One node-wide metrics segment; create() on the node, attach()
    from workers."""

    def __init__(self, handle: int, name: str, owner: bool):
        self._lib = _load()
        self._h = handle
        self.name = name
        self._owner = owner
        self._name_size = self._lib.metrics_name_size()
        self._num_buckets = self._lib.metrics_num_buckets()

    @classmethod
    def create(cls, name: str) -> "ShmMetricsRegistry":
        lib = _load()
        h = lib.metrics_create(name.encode())
        if not h:
            raise OSError(f"metrics_create({name!r}) failed")
        return cls(h, name, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmMetricsRegistry":
        lib = _load()
        h = lib.metrics_attach(name.encode())
        if not h:
            raise OSError(f"metrics_attach({name!r}) failed")
        return cls(h, name, owner=False)

    def close(self):
        if self._h:
            if self._owner:
                self._lib.metrics_destroy(self._h, self.name.encode())
            else:
                self._lib.metrics_detach(self._h)
            self._h = None

    # --- recording (lock-free in C++) -------------------------------------

    def counter_add(self, key: str, delta: float = 1.0):
        self._lib.metrics_counter_add(self._h, key.encode(), delta)

    def gauge_set(self, key: str, value: float):
        self._lib.metrics_gauge_set(self._h, key.encode(), value)

    def histogram_observe(self, key: str, value: float):
        self._lib.metrics_histogram_observe(self._h, key.encode(),
                                            value)

    # --- aggregation (head side) ------------------------------------------

    def read_all(self) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        n = self._lib.metrics_num_slots(self._h)
        name_buf = ctypes.create_string_buffer(self._name_size)
        value = ctypes.c_double()
        count = ctypes.c_uint64()
        total = ctypes.c_double()
        buckets = (ctypes.c_uint64 * self._num_buckets)()
        for i in range(n):
            t = self._lib.metrics_read_slot(
                self._h, i, name_buf, ctypes.byref(value),
                ctypes.byref(count), ctypes.byref(total), buckets)
            if t == 0:
                continue
            key = name_buf.value.decode(errors="replace")
            rec: Dict = {"type": {1: "counter", 2: "gauge",
                                  3: "histogram"}[t]}
            if t == TYPE_COUNTER:
                rec["value"] = value.value
                rec["num_samples"] = count.value
            elif t == TYPE_GAUGE:
                rec["value"] = value.value
            else:
                rec["count"] = count.value
                rec["sum"] = total.value
                rec["buckets"] = list(buckets)
            out[key] = rec
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition of the whole segment."""
        lines: List[str] = []
        for key, rec in sorted(self.read_all().items()):
            name = key.split("|", 1)[0]
            tags = ""
            if "|" in key:
                raw = key.split("|", 1)[1]
                pairs = [p.split("=", 1) for p in raw.split(",") if p]
                tags = "{" + ",".join(
                    f'{k}="{v}"' for k, v in pairs) + "}"
            if rec["type"] == "histogram":
                lines.append(f"# TYPE {name} histogram")
                lines.append(f"{name}_count{tags} {rec['count']}")
                lines.append(f"{name}_sum{tags} {rec['sum']}")
            else:
                lines.append(f"# TYPE {name} {rec['type']}")
                lines.append(f"{name}{tags} {rec['value']}")
        return "\n".join(lines) + "\n"


def metric_key(name: str, tags: Optional[Dict[str, str]] = None) -> str:
    if not tags:
        return name
    return name + "|" + ",".join(
        f"{k}={v}" for k, v in sorted(tags.items()))
