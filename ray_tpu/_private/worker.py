"""Global worker: process-wide connection to a runtime.

Capability parity with the reference's Worker singleton + ``ray.init``
bootstrapping (python/ray/_private/worker.py:404,1022). The runtime behind it
is pluggable: LocalRuntime (in-process, default and test fake) or the
distributed node runtime (ray_tpu.runtime, multi-process).
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Optional

from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import JobID
from ray_tpu._private.local_runtime import LocalRuntime
from ray_tpu._private.object_ref import set_global_reference_counter

logger = logging.getLogger(__name__)


class Worker:
    def __init__(self, runtime, mode: str):
        self.runtime = runtime
        self.mode = mode  # "local" | "node" | "driver" | "worker" | "client"
        self.namespace = "default"


_lock = threading.Lock()
_worker: Optional[Worker] = None


def is_initialized() -> bool:
    return _worker is not None


def global_worker() -> Worker:
    if _worker is None:
        # Auto-init like the reference does on first API use. Two threads
        # may race here; init() resolves it under its lock.
        init(ignore_reinit_error=True)
    return _worker


def _detect_tpu_chips() -> int:
    """Count local TPU chips without forcing a jax import unless one is
    plausibly present."""
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        return 0
    try:
        import jax
        return sum(1 for d in jax.devices()
                   if d.platform not in ("cpu",))
    except Exception:
        return 0


def init(address: Optional[str] = None,
         num_cpus: Optional[int] = None,
         num_tpus: Optional[int] = None,
         resources: Optional[Dict[str, float]] = None,
         namespace: Optional[str] = None,
         ignore_reinit_error: bool = False,
         _system_config: Optional[Dict[str, Any]] = None,
         log_to_driver: bool = True) -> Worker:
    """Connect this process to a runtime (starting one if needed)."""
    global _worker
    with _lock:
        if _worker is not None:
            if ignore_reinit_error:
                return _worker
            raise RuntimeError(
                "ray_tpu.init() called twice; pass "
                "ignore_reinit_error=True to ignore")
        if _system_config:
            GlobalConfig.apply_system_config(_system_config)

        if address in (None, "local"):
            res: Dict[str, float] = dict(resources or {})
            res.setdefault("CPU", float(num_cpus if num_cpus is not None
                                        else max(4, os.cpu_count() or 4)))
            tpus = (num_tpus if num_tpus is not None
                    else _detect_tpu_chips())
            if tpus:
                res.setdefault("TPU", float(tpus))
            res.setdefault("memory", 8 * 1024 ** 3)
            runtime = LocalRuntime(res, job_id=JobID.next())
            _worker = Worker(runtime, mode="local")
        elif address.startswith("ray://"):
            # Proxied remote driver (Ray Client parity): one endpoint,
            # no cluster network/shm access needed on this machine.
            from ray_tpu.runtime.client_proxy import ProxyRuntime
            runtime = ProxyRuntime(address[len("ray://"):])
            _worker = Worker(runtime, mode="client")
        else:
            # Distributed attach (node runtime); implemented in
            # ray_tpu.runtime.client.
            from ray_tpu.runtime.client import connect_to_cluster
            runtime = connect_to_cluster(address)
            if log_to_driver:
                runtime.start_log_streaming()
            _worker = Worker(runtime, mode="driver")
        if namespace:
            _worker.namespace = namespace
        set_global_reference_counter(runtime.ref_counter)
        plane = getattr(runtime, "plane", None)
        if plane is not None:
            from ray_tpu._private.object_ref import set_borrow_notifier
            set_borrow_notifier(plane.note_borrow)
        return _worker


def shutdown():
    global _worker
    with _lock:
        if _worker is None:
            return
        set_global_reference_counter(None)
        from ray_tpu._private.object_ref import set_borrow_notifier
        set_borrow_notifier(None)
        try:
            _worker.runtime.shutdown()
        finally:
            _worker = None
