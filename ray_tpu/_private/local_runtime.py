"""Local (single-process) runtime: the full task/actor/object semantics of the
framework executed with threads in one process.

This is the analogue of the reference's local-mode runtime
(cpp/src/ray/runtime/task/local_mode_task_submitter.cc) grown to full
capability: resource-gated scheduling (reference semantics:
src/ray/raylet/scheduling/cluster_task_manager.cc +
local_task_manager.cc), ordered/async/threaded actors with restart
(src/ray/core_worker/transport/direct_actor_task_submitter.cc,
gcs_actor_manager.cc:1037 ReconstructActor), task retries + lineage
reconstruction (src/ray/core_worker/task_manager.h:135,
object_recovery_manager.h:41), placement-group reservation
(gcs_placement_group_scheduler.h 2PC), named actors, cancellation, chaos
delay injection (common/asio/asio_chaos.cc), and a task timeline
(core_worker/profiling.h).

It doubles as the in-process test fake for every library layer, exactly the
role local mode plays in the reference.
"""
from __future__ import annotations

import asyncio
import collections
import inspect
import logging
import os
import queue
import random
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import profiling
from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import (ActorID, JobID, ObjectID,
                                  PlacementGroupID, TaskID)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import MemoryStore, ReferenceCounter
from ray_tpu._private.task_spec import (ActorCreationSpec, Bundle,
                                        PlacementGroupSchedulingStrategy,
                                        PlacementGroupSpec, TaskSpec)
from ray_tpu.exceptions import (ActorDiedError, ObjectLostError,
                                PendingCallsLimitExceeded,
                                TaskCancelledError, TaskError)

logger = logging.getLogger(__name__)

_exec_ctx = threading.local()


def current_task_context():
    return getattr(_exec_ctx, "ctx", None)


class _TaskContext:
    __slots__ = ("spec", "runtime", "resources_held")

    def __init__(self, spec, runtime):
        self.spec = spec
        self.runtime = runtime
        self.resources_held = True


class ResourcePool:
    """Node resource accounting with fractional amounts (the reference uses
    fixed-point arithmetic, scheduling/fixed_point.h; floats + epsilon here)."""

    EPS = 1e-9

    def __init__(self, total: Dict[str, float]):
        self.total = dict(total)
        self.available = dict(total)
        self._cv = threading.Condition()

    def fits(self, req: Dict[str, float]) -> bool:
        return all(self.available.get(k, 0.0) + self.EPS >= v
                   for k, v in req.items())

    def can_ever_fit(self, req: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) + self.EPS >= v
                   for k, v in req.items())

    def try_acquire(self, req: Dict[str, float]) -> bool:
        with self._cv:
            if not self.fits(req):
                return False
            for k, v in req.items():
                self.available[k] = self.available.get(k, 0.0) - v
            return True

    def acquire(self, req: Dict[str, float],
                timeout: Optional[float] = None) -> bool:
        """Block until the request fits (or timeout). Returns success."""
        deadline = None if timeout is None else time.time() + timeout
        with self._cv:
            while not self.fits(req):
                remaining = None if deadline is None else \
                    deadline - time.time()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(timeout=remaining if remaining is not None
                              else 1.0)
            for k, v in req.items():
                self.available[k] = self.available.get(k, 0.0) - v
            return True

    def release(self, req: Dict[str, float]):
        with self._cv:
            for k, v in req.items():
                self.available[k] = min(self.total.get(k, 0.0),
                                        self.available.get(k, 0.0) + v)
            self._cv.notify_all()

    def add_capacity(self, extra: Dict[str, float]):
        with self._cv:
            for k, v in extra.items():
                self.total[k] = self.total.get(k, 0.0) + v
                self.available[k] = self.available.get(k, 0.0) + v
            self._cv.notify_all()

    def remove_capacity(self, extra: Dict[str, float]):
        with self._cv:
            for k, v in extra.items():
                self.total[k] = self.total.get(k, 0.0) - v
                self.available[k] = self.available.get(k, 0.0) - v
            self._cv.notify_all()


class _ActorState:
    def __init__(self, spec: ActorCreationSpec, runtime: "LocalRuntime"):
        self.spec = spec
        self.runtime = runtime
        self.instance: Any = None
        self.dead = False
        self.death_reason = ""
        self.num_restarts = 0
        self.restarting = False
        from ray_tpu._private.concurrency_groups import GroupMailboxes
        self.gm = GroupMailboxes(spec.concurrency_groups,
                                 max(1, spec.max_concurrency))
        self.pending_count = 0
        self.lock = threading.RLock()
        self.threads: List[threading.Thread] = []
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.created = threading.Event()
        self.init_error: Optional[BaseException] = None

    # --- lifecycle ---------------------------------------------------------

    def start(self):
        if self.spec.is_async:
            t = threading.Thread(target=self._async_loop, daemon=True,
                                 name=f"actor-{self.spec.actor_id.hex()[:8]}")
            t.start()
            self.threads = [t]
        else:
            self.threads = []
            for group, box in self.gm.items():
                for i in range(self.gm.size(group)):
                    t = threading.Thread(
                        target=self._thread_loop, args=(box,),
                        daemon=True,
                        name=f"actor-{self.spec.actor_id.hex()[:8]}"
                             f"-{group}-{i}")
                    t.start()
                    self.threads.append(t)

    def _instantiate(self):
        try:
            profiling.record("actor_init", self.spec.cls.__name__)
            from ray_tpu._private.runtime_env import runtime_env_context
            with runtime_env_context(self.spec.runtime_env):
                self.instance = self.spec.cls(*self.spec.args,
                                              **self.spec.kwargs)
            self.init_error = None
        except BaseException as e:  # noqa: BLE001
            self.init_error = e
            self.dead = True
            self.death_reason = f"__init__ failed: {e!r}"
        finally:
            self.created.set()

    def _thread_loop(self, box: "queue.Queue"):
        # First thread instantiates.
        if not self.created.is_set():
            with self.lock:
                if not self.created.is_set():
                    self._instantiate()
        self.created.wait()
        while True:
            item = box.get()
            if item is None:
                return
            spec, ctx_runtime = item
            with self.lock:
                self.pending_count -= 1
            if self.dead:
                ctx_runtime._store_error(
                    spec, ActorDiedError(self.spec.actor_id,
                                         self.death_reason))
                continue
            ctx_runtime._execute_actor_task(self, spec)

    def _async_loop(self):
        loop = asyncio.new_event_loop()
        self.loop = loop
        asyncio.set_event_loop(loop)
        # The loop's DEFAULT executor sizes to min(32, cpus + 4) —
        # on a small host that silently caps run_in_executor offloads
        # (serve replicas run sync user methods there) far below the
        # actor's declared max_concurrency. Size it to the actor's
        # own concurrency; threads spawn lazily.
        # + one thread per group: each group's pump parks a blocking
        # box.get in this same pool while idle
        from concurrent.futures import ThreadPoolExecutor
        loop.set_default_executor(ThreadPoolExecutor(
            max_workers=self.gm.max_concurrency + len(self.gm.boxes),
            thread_name_prefix="actor-exec"))
        self._instantiate()
        # per-group semaphores bound concurrency independently
        sems = {g: asyncio.Semaphore(self.gm.size(g))
                for g, _ in self.gm.items()}

        async def pump(box, sem):
            while True:
                item = await loop.run_in_executor(None, box.get)
                if item is None:
                    return
                spec, ctx_runtime = item
                with self.lock:
                    self.pending_count -= 1
                if self.dead:
                    ctx_runtime._store_error(
                        spec, ActorDiedError(self.spec.actor_id,
                                             self.death_reason))
                    continue

                async def run_one(spec=spec):
                    async with sem:
                        await ctx_runtime._execute_actor_task_async(
                            self, spec)

                loop.create_task(run_one())

        async def pump_all():
            await asyncio.gather(*[
                pump(box, sems[g])
                for g, box in self.gm.items()])

        try:
            loop.run_until_complete(pump_all())
            pending = [t for t in asyncio.all_tasks(loop)
                       if not t.done()]
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
        finally:
            loop.close()

    def submit(self, spec: TaskSpec, runtime: "LocalRuntime"):
        with self.lock:
            if self.dead and not self.restarting:
                # dead-actor contract first: callers uniformly get an
                # ActorDiedError via the ref, even with a bad group
                runtime._store_error(
                    spec, ActorDiedError(self.spec.actor_id,
                                         self.death_reason))
                return
            box = self.gm.route(
                getattr(spec, "concurrency_group", None))
            limit = self.spec.max_pending_calls
            if limit and limit > 0 and self.pending_count >= limit:
                raise PendingCallsLimitExceeded(
                    f"actor {self.spec.actor_id.hex()[:8]} has "
                    f"{self.pending_count} pending calls (limit {limit})")
            self.pending_count += 1
        box.put((spec, runtime))

    def stop(self):
        if self.spec.is_async:
            self.gm.stop_one_per_group()
        else:
            self.gm.stop()


class PlacementGroup:
    """User-facing placement group handle (reference:
    python/ray/util/placement_group.py)."""

    def __init__(self, spec: PlacementGroupSpec, runtime: "LocalRuntime"):
        self.spec = spec
        self._runtime = runtime
        self._ready_event = threading.Event()
        self._removed = False
        self._state_lock = threading.Lock()

    @property
    def id(self) -> PlacementGroupID:
        return self.spec.pg_id

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return [dict(b.resources) for b in self.spec.bundles]

    def ready(self) -> ObjectRef:
        """An ObjectRef resolving when all bundles are reserved."""
        oid = ObjectID.from_random()
        ref = ObjectRef(oid)

        def _wait():
            self._ready_event.wait()
            self._runtime.store.put(oid, self)

        threading.Thread(target=_wait, daemon=True).start()
        return ref

    def wait(self, timeout_seconds: float = 30) -> bool:
        return self._ready_event.wait(timeout_seconds)

    def is_ready(self) -> bool:
        return self._ready_event.is_set()


class LocalRuntime:
    """Single-process runtime implementing the full API surface."""

    def __init__(self, resources: Dict[str, float],
                 job_id: Optional[JobID] = None):
        self.job_id = job_id or JobID.next()
        self.store = MemoryStore()
        self.ref_counter = ReferenceCounter(
            on_object_released=self._on_object_released)
        self.pool = ResourcePool(resources)
        self._lock = threading.RLock()
        self._actors: Dict[ActorID, _ActorState] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._actor_handles: Dict[ActorID, Any] = {}
        self._pending: collections.deque = collections.deque()
        self._cancelled: set = set()
        self._tasks_by_id: Dict[TaskID, TaskSpec] = {}
        self._task_states: Dict[TaskID, str] = {}
        self._lineage: Dict[ObjectID, TaskSpec] = {}
        self._lineage_bytes = 0
        self._pgs: Dict[PlacementGroupID, PlacementGroup] = {}
        self._shutdown = False
        self._sched_cv = threading.Condition()
        self._memory_monitor = None
        if GlobalConfig.memory_monitor_threshold > 0:
            from ray_tpu._private.memory_monitor import MemoryMonitor
            self._memory_monitor = MemoryMonitor(
                threshold=GlobalConfig.memory_monitor_threshold,
                check_interval_s=(
                    GlobalConfig.memory_monitor_interval_ms / 1000.0),
                on_threshold=lambda f: logger.warning(
                    "Memory pressure: %.0f%% used — pausing task "
                    "dispatch (reference: raylet MemoryMonitor OOM "
                    "prevention)", f * 100),
                on_recovered=lambda f: self._kick_scheduler(),
            ).start()
        self._sched_thread = threading.Thread(
            target=self._scheduler_loop, daemon=True, name="local-scheduler")
        self._sched_thread.start()

    # --- chaos -------------------------------------------------------------

    def _chaos_delay(self):
        from ray_tpu._private.config import chaos_delay
        chaos_delay()

    # --- objects -----------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        self._chaos_delay()
        oid = ObjectID.from_random()
        self.store.put(oid, value)
        return ObjectRef(oid)

    def object_future(self, oid: ObjectID) -> Future:
        return self.store.future(oid)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(
                    f"get() expects ObjectRef(s), got {type(r).__name__}")
        ctx = current_task_context()
        # Release held resources while blocked (prevents nested-task
        # deadlock; the reference achieves this by leasing new workers).
        released = False
        if ctx is not None and ctx.resources_held and any(
                not self.store.contains(r.id) for r in ref_list):
            self.pool.release(ctx.spec.resources)
            ctx.resources_held = False
            released = True
            self._kick_scheduler()
        try:
            # One overall deadline across all refs, not per-ref.
            deadline = None if timeout is None else time.time() + timeout
            values = []
            for r in ref_list:
                remaining = None if deadline is None else \
                    max(0.0, deadline - time.time())
                values.append(self.store.get(r.id, remaining))
        finally:
            if released:
                # Resume immediately even if the resources were taken in
                # the meantime (temporary oversubscription, matching the
                # reference's unblocked-worker semantics). resources_held
                # tracks whether re-acquisition succeeded so the ledger
                # stays balanced: release at task end only if held.
                ctx.resources_held = self.pool.try_acquire(
                    ctx.spec.resources)
        return values[0] if single else values

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        id_map = {r.id: r for r in refs}
        ready_ids, rest_ids = self.store.wait(
            [r.id for r in refs], num_returns, timeout)
        return ([id_map[i] for i in ready_ids],
                [id_map[i] for i in rest_ids])

    def _on_object_released(self, oid: ObjectID):
        # Out-of-scope objects are evicted (distributed GC capability).
        self.store.delete(oid)
        with self._lock:
            self._lineage.pop(oid, None)

    # --- normal tasks ------------------------------------------------------

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        self._chaos_delay()
        refs = []
        for oid in spec.return_ids:
            refs.append(ObjectRef(oid))
            self.ref_counter.set_lineage(oid, spec.task_id)
        with self._lock:
            self._tasks_by_id[spec.task_id] = spec
            self._task_states[spec.task_id] = "PENDING"
            for oid in spec.return_ids:
                self._lineage[oid] = spec
        for a in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(a, ObjectRef):
                self.ref_counter.add_submitted_task_ref(a.id)
        with self._sched_cv:
            self._pending.append(spec)
            self._sched_cv.notify_all()
        profiling.record("task_submitted", spec.name)
        return refs

    def _kick_scheduler(self):
        with self._sched_cv:
            self._sched_cv.notify_all()

    def _scheduler_loop(self):
        while not self._shutdown:
            with self._sched_cv:
                dispatched = self._try_dispatch()
                if not dispatched:
                    self._sched_cv.wait(timeout=0.05)

    def _try_dispatch(self) -> bool:
        """Dispatch every queued task whose resources fit. Returns True if
        any dispatch happened."""
        any_dispatched = False
        if self._memory_monitor is not None and \
                self._memory_monitor.above_threshold:
            # Above the watermark: stop starting new work until usage
            # drops (on_recovered kicks the scheduler).
            return False
        still_pending = collections.deque()
        while self._pending:
            spec = self._pending.popleft()
            if spec.task_id in self._cancelled:
                self._store_error(spec, TaskCancelledError(spec.task_id))
                continue
            req = self._effective_resources(spec)
            if req is None:
                # PG not ready yet.
                still_pending.append(spec)
                continue
            if self.pool.try_acquire(req):
                self._task_states[spec.task_id] = "RUNNING"
                t = threading.Thread(target=self._run_task,
                                     args=(spec, req), daemon=True,
                                     name=f"task-{spec.name[:24]}")
                t.start()
                any_dispatched = True
            else:
                if not self.pool.can_ever_fit(req):
                    self._store_error(spec, ValueError(
                        f"Task {spec.name} requires {req} but the cluster "
                        f"total is {self.pool.total} (infeasible)"))
                    continue
                still_pending.append(spec)
        self._pending = still_pending
        return any_dispatched

    def _effective_resources(self, spec: TaskSpec) -> Optional[Dict]:
        strat = spec.scheduling_strategy
        if isinstance(strat, PlacementGroupSchedulingStrategy) and \
                strat.placement_group is not None:
            pg = strat.placement_group
            if not pg.is_ready():
                return None
            # Resources were pre-reserved by the PG: the task runs inside
            # the reservation, so the node pool sees zero demand.
            return {}
        return spec.resources

    def _resolve_args(self, spec: TaskSpec):
        args = []
        for a in spec.args:
            args.append(self.store.get(a.id) if isinstance(a, ObjectRef)
                        else a)
        kwargs = {}
        for k, v in spec.kwargs.items():
            kwargs[k] = self.store.get(v.id) if isinstance(v, ObjectRef) \
                else v
        return args, kwargs

    def _release_task_arg_refs(self, spec: TaskSpec):
        for a in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(a, ObjectRef):
                self.ref_counter.remove_submitted_task_ref(a.id)

    def _run_task(self, spec: TaskSpec, acquired: Dict[str, float]):
        ctx = _TaskContext(spec, self)
        _exec_ctx.ctx = ctx
        self._chaos_delay()
        profiling.record_span_start("task_run", spec.name, spec.task_id)
        try:
            args, kwargs = self._resolve_args(spec)
            if spec.task_id in self._cancelled:
                raise TaskCancelledError(spec.task_id)
            from ray_tpu._private.runtime_env import runtime_env_context
            from ray_tpu.util.tracing import execution_span
            with runtime_env_context(spec.runtime_env), \
                    execution_span(spec.name, "task", spec.trace_ctx):
                result = spec.func(*args, **kwargs)
            self._store_returns(spec, result)
            self._task_states[spec.task_id] = "FINISHED"
        except TaskCancelledError as e:
            self._store_error(spec, e, wrap=False)
            self._task_states[spec.task_id] = "CANCELLED"
        except BaseException as e:  # noqa: BLE001
            self._handle_task_failure(spec, e)
        finally:
            self._release_task_arg_refs(spec)
            profiling.record_span_end("task_run", spec.name, spec.task_id)
            _exec_ctx.ctx = None
            if ctx.resources_held:
                self.pool.release(acquired)
            self._kick_scheduler()

    def _should_retry(self, spec: TaskSpec, exc: BaseException) -> bool:
        max_retries = spec.max_retries
        if spec.attempt >= max_retries:
            return False
        re = spec.retry_exceptions
        if re is True:
            return True
        if isinstance(re, (list, tuple)):
            return isinstance(exc, tuple(re))
        # retry_exceptions=False: only system failures retry; application
        # exceptions do not (reference semantics). Local runtime models
        # system failure as NodeDiedError/ObjectLostError.
        return isinstance(exc, (ObjectLostError,))

    def _handle_task_failure(self, spec: TaskSpec, exc: BaseException):
        if self._should_retry(spec, exc):
            delay = GlobalConfig.task_retry_delay_ms / 1000.0
            spec.attempt += 1
            logger.warning("Retrying task %s (attempt %d/%d) after %r",
                           spec.name, spec.attempt, spec.max_retries, exc)
            self._task_states[spec.task_id] = "PENDING_RETRY"

            def _resubmit():
                if delay:
                    time.sleep(delay)
                with self._sched_cv:
                    self._pending.append(spec)
                    self._sched_cv.notify_all()
            threading.Thread(target=_resubmit, daemon=True).start()
        else:
            self._store_error(spec, exc)
            self._task_states[spec.task_id] = "FAILED"

    def _put_return(self, oid: ObjectID, value: Any,
                    is_exception: bool = False):
        self.store.put(oid, value, is_exception=is_exception)
        # Fire-and-forget: if every ref to this return was already
        # dropped, evict immediately instead of leaking the entry.
        if self.ref_counter.ref_count(oid) == 0:
            self.store.delete(oid)
            with self._lock:
                self._lineage.pop(oid, None)

    def _store_returns(self, spec: TaskSpec, result: Any):
        n = spec.num_returns
        if n == 0:
            return
        if n == 1:
            self._put_return(spec.return_ids[0], result)
            return
        try:
            values = list(result)
        except TypeError:
            raise TypeError(
                f"Task {spec.name} declared num_returns={n} but returned "
                f"non-iterable {type(result).__name__}") from None
        if len(values) != n:
            raise ValueError(
                f"Task {spec.name} declared num_returns={n} but returned "
                f"{len(values)} values")
        for oid, v in zip(spec.return_ids, values):
            self._put_return(oid, v)

    def _store_error(self, spec: TaskSpec, exc: BaseException,
                     wrap: bool = True):
        if wrap and not isinstance(exc, (TaskError, ActorDiedError,
                                         TaskCancelledError,
                                         ObjectLostError)):
            exc = TaskError(exc, task_name=spec.name)
        for oid in spec.return_ids:
            self._put_return(oid, exc, is_exception=True)

    # --- lineage reconstruction -------------------------------------------

    def reconstruct_object(self, ref: ObjectRef) -> bool:
        """Re-execute the creating task of a lost object (reference:
        object_recovery_manager.h). Returns False if lineage is gone."""
        with self._lock:
            spec = self._lineage.get(ref.id)
        if spec is None:
            return False
        self.store.mark_lost(ref.id)
        clone = TaskSpec(**{f.name: getattr(spec, f.name)
                            for f in spec.__dataclass_fields__.values()})
        clone.attempt = 0
        with self._sched_cv:
            self._pending.append(clone)
            self._sched_cv.notify_all()
        return True

    def simulate_object_loss(self, ref: ObjectRef):
        """Test/chaos hook: drop the stored value (keeps lineage)."""
        self.store.mark_lost(ref.id)

    # --- cancellation ------------------------------------------------------

    def cancel(self, ref: ObjectRef, force: bool = False,
               recursive: bool = True):
        tid = ref.id.task_id()
        self._cancelled.add(tid)
        with self._lock:
            spec = self._tasks_by_id.get(tid)
        if spec is not None and self._task_states.get(tid) in (
                "PENDING", "PENDING_RETRY"):
            self._store_error(spec, TaskCancelledError(tid), wrap=False)
        self._kick_scheduler()

    # --- actors ------------------------------------------------------------

    def create_actor(self, spec: ActorCreationSpec) -> "_ActorState":
        self._chaos_delay()
        if spec.name:
            key = (spec.namespace or "default", spec.name)
            with self._lock:
                if key in self._named_actors:
                    existing = self._actors.get(self._named_actors[key])
                    if existing is not None and not existing.dead:
                        if spec.get_if_exists:
                            return existing
                        raise ValueError(
                            f"Actor name {spec.name!r} already taken")
        if not self.pool.try_acquire(spec.resources):
            if not self.pool.can_ever_fit(spec.resources):
                raise ValueError(
                    f"Actor requires {spec.resources}, cluster total "
                    f"{self.pool.total} (infeasible)")
            # Block until resources free (actors queue like tasks). If the
            # caller is itself a task holding resources, release them while
            # blocked — same nested-deadlock avoidance as get().
            ctx = current_task_context()
            released = False
            if ctx is not None and ctx.resources_held:
                self.pool.release(ctx.spec.resources)
                ctx.resources_held = False
                released = True
                self._kick_scheduler()
            try:
                if not self.pool.acquire(spec.resources, timeout=300):
                    raise TimeoutError(
                        f"Timed out acquiring {spec.resources} for actor")
            finally:
                if released:
                    # Same oversubscription semantics as get() above.
                    ctx.resources_held = self.pool.try_acquire(
                        ctx.spec.resources)
        state = _ActorState(spec, self)
        with self._lock:
            self._actors[spec.actor_id] = state
            if spec.name:
                self._named_actors[(spec.namespace or "default",
                                    spec.name)] = spec.actor_id
        state.start()
        return state

    def get_actor_state(self, actor_id: ActorID) -> _ActorState:
        with self._lock:
            st = self._actors.get(actor_id)
        if st is None:
            raise ActorDiedError(actor_id, "unknown actor")
        return st

    def lookup_named_actor(self, name: str,
                           namespace: Optional[str]) -> ActorID:
        with self._lock:
            key = (namespace or "default", name)
            if key not in self._named_actors:
                raise ValueError(f"No actor named {name!r}")
            return self._named_actors[key]

    def submit_actor_task(self, actor_id: ActorID,
                          spec: TaskSpec) -> List[ObjectRef]:
        self._chaos_delay()
        refs = [ObjectRef(oid) for oid in spec.return_ids]
        with self._lock:
            self._tasks_by_id[spec.task_id] = spec
            self._task_states[spec.task_id] = "PENDING_ACTOR"
        st = self.get_actor_state(actor_id)
        try:
            st.submit(spec, self)
        except BaseException:
            # rejected at submit (unknown concurrency group, pending
            # limit): drop the phantom task record
            with self._lock:
                self._tasks_by_id.pop(spec.task_id, None)
                self._task_states.pop(spec.task_id, None)
            raise
        return refs

    def _execute_actor_task(self, st: _ActorState, spec: TaskSpec):
        ctx = _TaskContext(spec, self)
        ctx.resources_held = False   # actor holds its own resources
        _exec_ctx.ctx = ctx
        profiling.record_span_start("actor_task", spec.name, spec.task_id)
        try:
            if st.init_error is not None:
                raise ActorDiedError(st.spec.actor_id, st.death_reason)
            args, kwargs = self._resolve_args(spec)
            method = getattr(st.instance, spec.method_name)
            from ray_tpu._private.runtime_env import runtime_env_context
            from ray_tpu.util.tracing import execution_span
            with runtime_env_context(st.spec.runtime_env), \
                    execution_span(spec.name, "actor_task",
                                   spec.trace_ctx):
                result = method(*args, **kwargs)
            self._store_returns(spec, result)
            self._task_states[spec.task_id] = "FINISHED"
        except BaseException as e:  # noqa: BLE001
            self._handle_actor_task_failure(st, spec, e)
        finally:
            profiling.record_span_end("actor_task", spec.name, spec.task_id)
            _exec_ctx.ctx = None

    async def _execute_actor_task_async(self, st: _ActorState,
                                        spec: TaskSpec):
        profiling.record_span_start("actor_task", spec.name, spec.task_id)
        try:
            if st.init_error is not None:
                raise ActorDiedError(st.spec.actor_id, st.death_reason)
            args, kwargs = self._resolve_args(spec)
            method = getattr(st.instance, spec.method_name)
            from ray_tpu._private.runtime_env import runtime_env_context
            with runtime_env_context(st.spec.runtime_env):
                result = method(*args, **kwargs)
                if inspect.isawaitable(result):
                    result = await result
            self._store_returns(spec, result)
            self._task_states[spec.task_id] = "FINISHED"
        except BaseException as e:  # noqa: BLE001
            self._handle_actor_task_failure(st, spec, e)
        finally:
            profiling.record_span_end("actor_task", spec.name, spec.task_id)

    def _handle_actor_task_failure(self, st: _ActorState, spec: TaskSpec,
                                   exc: BaseException):
        # Application exceptions do not kill the actor (reference
        # semantics); they are returned to the caller.
        if isinstance(exc, ActorDiedError):
            # Actor is dead: honor max_task_retries by re-submitting to
            # the (possibly restarted) actor.
            if spec.attempt < st.spec.max_task_retries and not (
                    st.dead and not st.restarting):
                spec.attempt += 1
                st.submit(spec, self)
                return
        self._store_error(spec, exc)
        self._task_states[spec.task_id] = "FAILED"

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        """Kill an actor. With no_restart=False this models a *crash* —
        the restart policy (max_restarts) applies, pending calls see
        ActorDiedError or are retried per max_task_retries."""
        st = self.get_actor_state(actor_id)
        with st.lock:
            st.dead = True
            st.death_reason = ("killed via kill()" if no_restart
                               else "worker crashed")
            can_restart = (not no_restart and
                           (st.spec.max_restarts == -1 or
                            st.num_restarts < st.spec.max_restarts))
            st.restarting = can_restart
        if can_restart:
            backoff = GlobalConfig.actor_restart_backoff_ms / 1000.0

            def _restart():
                if backoff:
                    time.sleep(backoff)
                with st.lock:
                    st.num_restarts += 1
                    st.dead = False
                    st.restarting = False
                    st.created.clear()
                    st.instance = None
                # Threads keep draining the mailbox; the next task
                # triggers re-instantiation.
                with st.lock:
                    if not st.created.is_set():
                        st._instantiate()
            threading.Thread(target=_restart, daemon=True).start()
        else:
            self.pool.release(st.spec.resources)
            st.stop()
            with self._lock:
                if st.spec.name:
                    self._named_actors.pop(
                        (st.spec.namespace or "default", st.spec.name),
                        None)

    # --- placement groups --------------------------------------------------

    def create_placement_group(self, spec: PlacementGroupSpec
                               ) -> PlacementGroup:
        pg = PlacementGroup(spec, self)
        with self._lock:
            self._pgs[spec.pg_id] = pg
        total: Dict[str, float] = {}
        for b in spec.bundles:
            for k, v in b.resources.items():
                total[k] = total.get(k, 0.0) + v

        def _reserve():
            deadline = time.time() + 300
            while True:
                if pg._removed:
                    return
                if self.pool.try_acquire(total):
                    break
                if not self.pool.can_ever_fit(total):
                    return  # infeasible: never ready (caller times out)
                if time.time() > deadline:
                    return
                time.sleep(0.005)
            with pg._state_lock:
                if pg._removed:
                    # Removed while we were acquiring: give it back.
                    self.pool.release(total)
                    return
                pg._ready_event.set()
        threading.Thread(target=_reserve, daemon=True).start()
        return pg

    def remove_placement_group(self, pg: PlacementGroup):
        with self._lock:
            self._pgs.pop(pg.id, None)
        with pg._state_lock:
            pg._removed = True
            was_ready = pg.is_ready()
            pg._ready_event.clear()
        if was_ready:
            total: Dict[str, float] = {}
            for b in pg.spec.bundles:
                for k, v in b.resources.items():
                    total[k] = total.get(k, 0.0) + v
            self.pool.release(total)

    # --- introspection -----------------------------------------------------

    def cluster_resources(self) -> Dict[str, float]:
        return dict(self.pool.total)

    def available_resources(self) -> Dict[str, float]:
        return dict(self.pool.available)

    def list_actors(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for aid, st in self._actors.items():
                out.append({
                    "actor_id": aid.hex(),
                    "class_name": st.spec.cls.__name__,
                    "state": ("DEAD" if st.dead else
                              "RESTARTING" if st.restarting else "ALIVE"),
                    "name": st.spec.name or "",
                    "num_restarts": st.num_restarts,
                    "pending_tasks": st.pending_count,
                })
            return out

    def list_tasks(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"task_id": tid.hex(),
                     "name": spec.name,
                     "state": self._task_states.get(tid, "UNKNOWN")}
                    for tid, spec in self._tasks_by_id.items()]

    def list_objects(self) -> List[Dict[str, Any]]:
        out = []
        for oid in self.store.keys():
            out.append({"object_id": oid.hex(),
                        "ref_count": self.ref_counter.ref_count(oid),
                        "ready": self.store.contains(oid)})
        return out

    # --- shutdown ----------------------------------------------------------

    def shutdown(self):
        self._shutdown = True
        if self._memory_monitor is not None:
            self._memory_monitor.stop()
        self._kick_scheduler()
        with self._lock:
            actors = list(self._actors.values())
        for st in actors:
            st.stop()
        self.ref_counter.enabled = False
