"""Node memory watermark monitor.

Capability parity with the reference's MemoryMonitor
(src/ray/common/memory_monitor.h:48 wired into the raylet at
node_manager.h:853, Python counterpart _private/memory_monitor.py:94):
a watermark thread that reads node memory usage and triggers a callback
above the threshold so the runtime can shed load (refuse/kill tasks)
before the OS OOM-killer does.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple


def read_proc_meminfo() -> Tuple[int, int]:
    """Returns (used_bytes, total_bytes) from /proc/meminfo."""
    total = avail = None
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith("MemAvailable:"):
                avail = int(line.split()[1]) * 1024
            if total is not None and avail is not None:
                break
    if total is None or avail is None:
        raise RuntimeError("Could not parse /proc/meminfo")
    return total - avail, total


class MemoryMonitor:
    """Polls a usage provider; fires ``on_threshold(fraction)`` when the
    used fraction crosses ``threshold`` and ``on_recovered(fraction)``
    when it drops back under."""

    def __init__(self, threshold: float = 0.95,
                 check_interval_s: float = 1.0,
                 usage_provider: Optional[
                     Callable[[], Tuple[int, int]]] = None,
                 on_threshold: Optional[Callable[[float], None]] = None,
                 on_recovered: Optional[Callable[[float], None]] = None):
        self.threshold = threshold
        self._interval = check_interval_s
        self._provider = usage_provider or read_proc_meminfo
        self._on_threshold = on_threshold
        self._on_recovered = on_recovered
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.above_threshold = False
        self.last_fraction = 0.0

    def check_once(self) -> bool:
        """One poll; returns True if above threshold. Usable without the
        thread (tests, or inline checks in a dispatch loop)."""
        used, total = self._provider()
        frac = used / max(total, 1)
        self.last_fraction = frac
        if frac >= self.threshold and not self.above_threshold:
            self.above_threshold = True
            if self._on_threshold:
                self._on_threshold(frac)
        elif frac < self.threshold and self.above_threshold:
            self.above_threshold = False
            if self._on_recovered:
                self._on_recovered(frac)
        return self.above_threshold

    def start(self) -> "MemoryMonitor":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="memory-monitor")
        self._thread.start()
        return self

    def stop(self):
        self._stopped.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self):
        while not self._stopped.is_set():
            try:
                self.check_once()
            except Exception:
                pass
            self._stopped.wait(self._interval)
