"""In-process object store + distributed reference counting.

Two reference capabilities re-designed for one process (the local runtime) and
reused by the node runtime:

- CoreWorkerMemoryStore (reference:
  src/ray/core_worker/store_provider/memory_store/memory_store.h): value slots
  with futures, async waiters, inlined small objects.
- ReferenceCounter (reference: src/ray/core_worker/reference_count.h):
  local refcounts, borrows, lineage pinning, eviction on zero refs.

Values are stored as Python objects (zero-copy within a process — the
distributed path serializes via ray_tpu._private.serialization, device arrays
are referenced, not copied: see mesh/device_objects.py).
"""
from __future__ import annotations

import sys
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Set

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu.exceptions import GetTimeoutError, ObjectLostError


def _sizeof(value: Any) -> int:
    try:
        import numpy as np
        if isinstance(value, np.ndarray):
            return value.nbytes
    except Exception:
        pass
    try:
        return sys.getsizeof(value)
    except Exception:
        return 64


class _Entry:
    __slots__ = ("value", "is_exception", "ready", "size", "create_time",
                 "pinned")

    def __init__(self):
        self.value = None
        self.is_exception = False
        self.ready = threading.Event()
        self.size = 0
        self.create_time = 0.0
        self.pinned = False


class MemoryStore:
    """Thread-safe keyed future store."""

    def __init__(self):
        self._lock = threading.RLock()
        self._entries: Dict[ObjectID, _Entry] = {}
        self._futures: Dict[ObjectID, List[Future]] = {}
        self.total_bytes = 0

    def _entry(self, oid: ObjectID) -> _Entry:
        e = self._entries.get(oid)
        if e is None:
            e = _Entry()
            self._entries[oid] = e
        return e

    def put(self, oid: ObjectID, value: Any, is_exception: bool = False):
        with self._lock:
            e = self._entry(oid)
            if e.ready.is_set():
                return  # immutable: first write wins
            e.value = value
            e.is_exception = is_exception
            e.size = _sizeof(value)
            e.create_time = time.time()
            self.total_bytes += e.size
            futures = self._futures.pop(oid, [])
        e.ready.set()
        for f in futures:
            self._resolve_future(f, e)

    @staticmethod
    def _resolve_future(f: Future, e: _Entry):
        if f.set_running_or_notify_cancel():
            if e.is_exception:
                f.set_exception(e.value)
            else:
                f.set_result(e.value)

    def future(self, oid: ObjectID) -> Future:
        f: Future = Future()
        with self._lock:
            e = self._entry(oid)
            if not e.ready.is_set():
                self._futures.setdefault(oid, []).append(f)
                return f
        self._resolve_future(f, e)
        return f

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(oid)
            return e is not None and e.ready.is_set()

    def get(self, oid: ObjectID, timeout: Optional[float] = None) -> Any:
        with self._lock:
            e = self._entry(oid)
        if not e.ready.wait(timeout):
            raise GetTimeoutError(
                f"Get timed out after {timeout}s waiting for "
                f"{oid.hex()[:16]}…")
        if e.is_exception:
            raise e.value
        return e.value

    def wait(self, oids: List[ObjectID], num_returns: int,
             timeout: Optional[float]) -> tuple:
        deadline = None if timeout is None else time.time() + timeout
        ready: List[ObjectID] = []
        remaining = list(oids)
        while True:
            still = []
            for oid in remaining:
                if self.contains(oid):
                    if oid not in ready:
                        ready.append(oid)
                else:
                    still.append(oid)
            remaining = still
            if len(ready) >= num_returns or not remaining:
                return ready, remaining
            if deadline is not None and time.time() >= deadline:
                return ready, remaining
            time.sleep(0.001)

    def delete(self, oid: ObjectID):
        with self._lock:
            e = self._entries.pop(oid, None)
            if e is not None and e.ready.is_set():
                self.total_bytes -= e.size
            for f in self._futures.pop(oid, []):
                if f.set_running_or_notify_cancel():
                    f.set_exception(ObjectLostError(oid, "deleted"))

    def mark_lost(self, oid: ObjectID, reason: str = "evicted"):
        """Drop a value but keep the slot pending (for reconstruction)."""
        with self._lock:
            e = self._entries.get(oid)
            if e is not None and e.ready.is_set():
                self.total_bytes -= e.size
                self._entries[oid] = _Entry()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            ready = sum(1 for e in self._entries.values()
                        if e.ready.is_set())
            return {"num_objects": len(self._entries),
                    "num_ready": ready,
                    "total_bytes": self.total_bytes}

    def keys(self) -> List[ObjectID]:
        with self._lock:
            return list(self._entries.keys())


class Reference:
    __slots__ = ("local_refs", "borrows", "submitted_task_refs",
                 "lineage_task", "on_zero")

    def __init__(self):
        self.local_refs = 0
        self.borrows = 0
        self.submitted_task_refs = 0
        self.lineage_task: Optional[TaskID] = None
        self.on_zero: Optional[Callable] = None

    def total(self) -> int:
        return self.local_refs + self.borrows + self.submitted_task_refs


class ReferenceCounter:
    """Per-process distributed-GC bookkeeping (local-runtime flavor: one
    process owns everything, borrows model refs held by tasks/actors)."""

    def __init__(self, on_object_released: Optional[Callable] = None):
        self._lock = threading.RLock()
        self._refs: Dict[ObjectID, Reference] = {}
        self._on_object_released = on_object_released
        self.enabled = True

    def _ref(self, oid: ObjectID) -> Reference:
        r = self._refs.get(oid)
        if r is None:
            r = Reference()
            self._refs[oid] = r
        return r

    def add_local_ref(self, oid: ObjectID, borrowed: bool = False):
        if not self.enabled:
            return
        with self._lock:
            r = self._ref(oid)
            if borrowed:
                r.borrows += 1
            else:
                r.local_refs += 1

    def remove_local_ref(self, oid: ObjectID):
        if not self.enabled:
            return
        released = False
        with self._lock:
            r = self._refs.get(oid)
            if r is None:
                return
            if r.borrows > 0 and r.local_refs == 0:
                r.borrows -= 1
            elif r.local_refs > 0:
                r.local_refs -= 1
            if r.total() <= 0:
                del self._refs[oid]
                released = True
        if released and self._on_object_released is not None:
            self._on_object_released(oid)

    def add_submitted_task_ref(self, oid: ObjectID):
        if not self.enabled:
            return
        with self._lock:
            self._ref(oid).submitted_task_refs += 1

    def remove_submitted_task_ref(self, oid: ObjectID):
        if not self.enabled:
            return
        released = False
        with self._lock:
            r = self._refs.get(oid)
            if r is None:
                return
            r.submitted_task_refs -= 1
            if r.total() <= 0:
                del self._refs[oid]
                released = True
        if released and self._on_object_released is not None:
            self._on_object_released(oid)

    def set_lineage(self, oid: ObjectID, task_id: TaskID):
        with self._lock:
            self._ref(oid).lineage_task = task_id

    def lineage(self, oid: ObjectID) -> Optional[TaskID]:
        with self._lock:
            r = self._refs.get(oid)
            return r.lineage_task if r else None

    def ref_count(self, oid: ObjectID) -> int:
        with self._lock:
            r = self._refs.get(oid)
            return r.total() if r else 0

    def live_objects(self) -> Set[ObjectID]:
        with self._lock:
            return set(self._refs.keys())

    def clear(self):
        with self._lock:
            self._refs.clear()
