"""ObjectRef: the client-side handle to a (possibly pending) object.

Capability parity with the reference's ObjectRef (python/ray/_raylet.pyx
ObjectRef + C++ reference_count.h): holding a ref pins the object; refs are
counted per-process and deserializing a ref inside a task registers a borrow
with the owner.
"""
from __future__ import annotations

import threading
from typing import Any, Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner_hint", "_weakref__", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hint: Optional[str] = None,
                 _register_borrow: bool = False, _skip_incref: bool = False):
        self.id = object_id
        self.owner_hint = owner_hint  # node/worker hint for the dist. runtime
        if not _skip_incref:
            rc = _global_reference_counter()
            if rc is not None:
                rc.add_local_ref(object_id, borrowed=_register_borrow)
            if _register_borrow and _borrow_notifier is not None:
                # Deserialized ref owned elsewhere: register the
                # borrow with the owner-side protocol (batched).
                try:
                    _borrow_notifier(object_id)
                except Exception:
                    pass    # worst case: LRU bounds the object

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from ray_tpu._private.worker import global_worker
        return global_worker().runtime.object_future(self.id)

    def __await__(self):
        """Support ``await ref`` inside async actors / async drivers."""
        import asyncio
        fut = self.future()
        loop = asyncio.get_event_loop()
        afut = loop.create_future()

        def _done(f):
            def _set():
                if afut.cancelled():
                    return
                exc = f.exception()
                if exc is not None:
                    afut.set_exception(exc)
                else:
                    afut.set_result(f.result())
            loop.call_soon_threadsafe(_set)

        fut.add_done_callback(_done)
        return afut.__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __del__(self):
        rc = _global_reference_counter()
        if rc is not None:
            try:
                rc.remove_local_ref(self.id)
            except Exception:
                pass

    def __reduce__(self):
        # A pickled ref is a ref ESCAPING this process (task arg,
        # nested in another object, shipped to an actor): promote the
        # object out of the owner's memory tier into shm first, or the
        # receiver could never resolve it (reference: in-process
        # memory_store objects are inlined/promoted when borrowed).
        _promote_if_local(self.id)
        return (_deserialize_ref, (self.id.binary(), self.owner_hint))


def _deserialize_ref(binary: bytes, owner_hint):
    return ObjectRef(ObjectID(binary), owner_hint=owner_hint,
                     _register_borrow=True)


def _promote_if_local(oid: ObjectID) -> None:
    """If any plane in this process owns `oid`, move it to shm so
    other processes can resolve the escaping ref. Checks EVERY live
    plane, not just the global worker's — the owner can be a
    non-global runtime (e.g. the client-proxy server's)."""
    try:
        from ray_tpu.runtime.object_plane import promote_everywhere
        promote_everywhere(oid)
    except Exception:
        pass    # no runtime / local runtime: nothing to promote
    import sys
    if "ray_tpu.mesh.device_objects" in sys.modules:
        # An escaping ref to an HBM-resident device object forces its
        # one host spill (mesh/device_objects.py module doc). Guarded
        # by sys.modules: a process that never registered a device
        # object has nothing to spill and skips the jax import. Spill
        # failures (device_get error, shm store full) propagate — the
        # pickle fails HERE at the root cause, instead of shipping a
        # ref whose payload will never exist and hanging the consumer.
        from ray_tpu.mesh.device_objects import spill_on_escape
        spill_on_escape(oid)


_rc_lock = threading.Lock()
_rc: Optional[Any] = None
_borrow_notifier: Optional[Any] = None


def _global_reference_counter():
    return _rc


def set_global_reference_counter(rc) -> None:
    global _rc
    with _rc_lock:
        _rc = rc


def set_borrow_notifier(fn) -> None:
    """Install the runtime's borrow-registration hook (the
    distributed runtimes pass their plane's note_borrow)."""
    global _borrow_notifier
    with _rc_lock:
        _borrow_notifier = fn
