"""Task/actor specification types.

Capability parity with the reference's TaskSpecification
(src/ray/common/task/task_spec.h) and option validation
(python/ray/_private/ray_option_utils.py), in a fresh dataclass form shared by
the local and distributed runtimes.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID


class SchedulingStrategy:
    """Base scheduling strategy (reference:
    python/ray/util/scheduling_strategies.py)."""


@dataclasses.dataclass
class DefaultSchedulingStrategy(SchedulingStrategy):
    pass


@dataclasses.dataclass
class SpreadSchedulingStrategy(SchedulingStrategy):
    pass


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    node_id: Any = None
    soft: bool = False


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclasses.dataclass
class SliceAffinitySchedulingStrategy(SchedulingStrategy):
    """TPU-native: schedule onto a specific ICI slice / sub-slice."""
    slice_id: Any = None
    soft: bool = False


_TASK_OPTION_DEFAULTS: Dict[str, Any] = {
    "num_returns": 1,
    "num_cpus": 1.0,
    "num_tpus": 0.0,
    "resources": None,
    "max_retries": None,        # None -> config default
    "retry_exceptions": False,
    "name": None,
    "scheduling_strategy": None,
    "runtime_env": None,
    "_metadata": None,
}

_ACTOR_OPTION_DEFAULTS: Dict[str, Any] = {
    # Actors reserve NO cpu for their lifetime unless asked (reference
    # semantics, python/ray/_private/ray_option_utils.py: actor num_cpus
    # defaults to 0 while running) — otherwise actor pools starve the
    # cluster and nested pools deadlock.
    "num_cpus": 0.0,
    "num_tpus": 0.0,
    "resources": None,
    "max_restarts": 0,
    "max_task_retries": 0,
    "max_concurrency": None,    # None -> 1 (sync) / 1000 (async)
    "max_pending_calls": -1,
    "name": None,
    "namespace": None,
    "lifetime": None,           # None | "detached"
    "get_if_exists": False,
    "scheduling_strategy": None,
    "runtime_env": None,
    "concurrency_groups": None,
}


def validate_task_options(options: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(_TASK_OPTION_DEFAULTS)
    for k, v in options.items():
        if k not in _TASK_OPTION_DEFAULTS:
            raise ValueError(f"Unknown task option: {k!r}")
        out[k] = v
    nr = out["num_returns"]
    if not (nr == "streaming" or (isinstance(nr, int) and nr >= 0)):
        raise ValueError("num_returns must be a non-negative int")
    for res in ("num_cpus", "num_tpus"):
        if out[res] is not None and out[res] < 0:
            raise ValueError(f"{res} must be >= 0")
    from ray_tpu._private.runtime_env import validate_runtime_env
    out["runtime_env"] = validate_runtime_env(out["runtime_env"])
    return out


def validate_actor_options(options: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(_ACTOR_OPTION_DEFAULTS)
    for k, v in options.items():
        if k not in _ACTOR_OPTION_DEFAULTS:
            raise ValueError(f"Unknown actor option: {k!r}")
        out[k] = v
    if out["max_restarts"] is not None and out["max_restarts"] < -1:
        raise ValueError("max_restarts must be >= -1 (-1 = infinite)")
    if out["lifetime"] not in (None, "detached", "non_detached"):
        raise ValueError("lifetime must be None or 'detached'")
    from ray_tpu._private.runtime_env import validate_runtime_env
    out["runtime_env"] = validate_runtime_env(out["runtime_env"])
    return out


def resources_from_options(opts: Dict[str, Any]) -> Dict[str, float]:
    res: Dict[str, float] = {}
    if opts.get("num_cpus"):
        res["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    extra = opts.get("resources") or {}
    for k, v in extra.items():
        if k in ("CPU", "TPU"):
            raise ValueError(
                f"Pass {k} via num_cpus/num_tpus, not resources=")
        res[k] = float(v)
    return res


@dataclasses.dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    name: str
    func: Optional[Callable]            # None for actor method by name
    args: Tuple[Any, ...]
    kwargs: Dict[str, Any]
    num_returns: int
    return_ids: List[ObjectID]
    resources: Dict[str, float]
    max_retries: int = 0
    retry_exceptions: Any = False       # bool | list[type]
    scheduling_strategy: Optional[SchedulingStrategy] = None
    runtime_env: Optional[Dict[str, Any]] = None
    # Actor fields
    actor_id: Optional[ActorID] = None
    method_name: Optional[str] = None
    # actor concurrency group the call executes in (None = default)
    concurrency_group: Optional[str] = None
    is_actor_creation: bool = False
    # Bookkeeping
    attempt: int = 0
    parent_task_id: Optional[TaskID] = None
    # Tracing context propagated caller -> executor (P18,
    # util/tracing/tracing_helper.py parity).
    trace_ctx: Optional[Dict[str, str]] = None

    @property
    def is_actor_task(self) -> bool:
        return self.actor_id is not None and not self.is_actor_creation


@dataclasses.dataclass
class ActorCreationSpec:
    actor_id: ActorID
    job_id: JobID
    cls: type
    args: Tuple[Any, ...]
    kwargs: Dict[str, Any]
    resources: Dict[str, float]
    max_restarts: int
    max_task_retries: int
    max_concurrency: int
    max_pending_calls: int
    name: Optional[str]
    namespace: Optional[str]
    lifetime: Optional[str]
    scheduling_strategy: Optional[SchedulingStrategy] = None
    runtime_env: Optional[Dict[str, Any]] = None
    concurrency_groups: Optional[Dict[str, int]] = None
    is_async: bool = False
    get_if_exists: bool = False


@dataclasses.dataclass
class Bundle:
    resources: Dict[str, float]
    index: int = -1


@dataclasses.dataclass
class PlacementGroupSpec:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    strategy: str = "PACK"   # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    name: str = ""
    lifetime: Optional[str] = None
