"""Runtime environments: per-task/actor execution environments.

Capability parity with the reference's runtime_env subsystem
(python/ray/_private/runtime_env/{working_dir,py_modules,plugin}.py and
the per-node agent dashboard/modules/runtime_env/runtime_env_agent.py:159):
``env_vars``, ``working_dir`` and ``py_modules`` are supported. The
reference isolates runtime envs by starting dedicated worker processes
keyed by the env (worker_pool.h:149); here the env is applied around each
execution under a process-wide lock — same observable semantics for
tasks, serialized only among tasks that carry a runtime_env. Zipped
``working_dir`` archives are staged into a URI-keyed cache the way the
agent caches working-dir URIs.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import sys
import threading
import zipfile
from typing import Any, Dict, Optional

_KNOWN_KEYS = {"env_vars", "working_dir", "py_modules", "pip",
               "conda", "container"}


def runtime_env_key(runtime_env: Optional[Dict[str, Any]]
                    ) -> Optional[str]:
    """Canonical content key for worker-pool routing (the reference
    keys dedicated worker processes by serialized runtime env,
    worker_pool.h:149)."""
    if not runtime_env:
        return None
    import json
    blob = json.dumps(runtime_env, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


_permanent_envs: list = []


def enter_runtime_env_permanently(runtime_env: Dict[str, Any]) -> None:
    """Apply a runtime env for the lifetime of this process (dedicated
    env-keyed workers apply their env once at startup; per-task
    apply/restore is then skipped entirely)."""
    ctx = runtime_env_context(runtime_env)
    ctx.__enter__()        # never exited: the process IS the env
    # Keep the suspended generator alive — dropping the last reference
    # would run its finally block and RESTORE the env.
    _permanent_envs.append(ctx)

# cwd / os.environ / sys.path are process-global; the lock guards only
# the apply/restore mutations (never user code — see
# runtime_env_context). Overlapping contexts are reconciled with
# per-key undo stacks and sys.path refcounts so any completion order
# restores the true original value.
_apply_lock = threading.RLock()

# key -> [[token, saved_value], ...] (oldest first). Restoring an entry
# that is not top-of-stack splices it out and hands its saved value to
# the entry above (which captured OUR value as its "old"), so the final
# restore still lands on the genuine original.
_env_stacks: Dict[str, list] = {}
_cwd_stack: list = []
_path_claims: Dict[str, int] = {}


def _stack_restore(stack: list, token: object, setter) -> None:
    for i, (tok, saved) in enumerate(stack):
        if tok is token:
            if i == len(stack) - 1:
                setter(saved)
            else:
                stack[i + 1][1] = saved
            del stack[i]
            return


def _claim_path(path: str) -> None:
    rec = _path_claims.get(path)
    if rec is not None:
        rec[1] += 1
        return
    inserted = path not in sys.path   # pre-existing entries aren't ours
    if inserted:
        sys.path.insert(0, path)
    _path_claims[path] = [inserted, 1]


def _release_path(path: str) -> None:
    rec = _path_claims.get(path)
    if rec is None:
        return
    rec[1] -= 1
    if rec[1] <= 0:
        _path_claims.pop(path, None)
        if rec[0]:
            try:
                sys.path.remove(path)
            except ValueError:
                pass

_CACHE_DIR = os.path.join("/tmp", "ray_tpu", "runtime_env_cache")


def validate_runtime_env(runtime_env: Optional[Dict[str, Any]]
                         ) -> Optional[Dict[str, Any]]:
    if runtime_env is None:
        return None
    if not isinstance(runtime_env, dict):
        raise TypeError("runtime_env must be a dict, got "
                        f"{type(runtime_env).__name__}")
    unknown = set(runtime_env) - _KNOWN_KEYS
    if unknown:
        raise ValueError(
            f"Unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(_KNOWN_KEYS)}")
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None:
        if not isinstance(env_vars, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in env_vars.items()):
            raise TypeError("runtime_env['env_vars'] must be a "
                            "Dict[str, str]")
    wd = runtime_env.get("working_dir")
    if wd is not None and not isinstance(wd, str):
        raise TypeError("runtime_env['working_dir'] must be a path str")
    mods = runtime_env.get("py_modules")
    if mods is not None and not isinstance(mods, (list, tuple)):
        raise TypeError("runtime_env['py_modules'] must be a list")
    conda = runtime_env.get("conda")
    if conda is not None and not isinstance(conda, (str, dict)):
        raise TypeError(
            "runtime_env['conda'] must be an env name (str) or an "
            "environment spec (dict with 'dependencies')")
    container = runtime_env.get("container")
    if container is not None:
        if not isinstance(container, dict) or \
                not container.get("image"):
            raise TypeError(
                "runtime_env['container'] must be a dict with an "
                "'image' key (and optional 'run_options' list)")
        ro = container.get("run_options", [])
        if not isinstance(ro, list) or \
                not all(isinstance(o, str) for o in ro):
            raise TypeError(
                "runtime_env['container']['run_options'] must be a "
                "list of strings")
    if conda is not None and runtime_env.get("pip") is not None:
        raise ValueError(
            "runtime_env cannot combine 'conda' and 'pip' (install "
            "pip packages via the conda spec's dependencies)")
    pip = runtime_env.get("pip")
    if pip is not None:
        if isinstance(pip, dict):
            pkgs = pip.get("packages")
        else:
            pkgs = pip
        if not isinstance(pkgs, (list, tuple)) or not all(
                isinstance(p, str) for p in pkgs):
            raise TypeError(
                "runtime_env['pip'] must be a list of requirement "
                "strings or {'packages': [...], 'local_index': path}")
    return dict(runtime_env)


# ---------------------------------------------------------------- pip envs

def _pip_spec(runtime_env: Dict[str, Any]):
    pip = runtime_env.get("pip")
    if pip is None:
        return None, None
    if isinstance(pip, dict):
        return list(pip.get("packages") or []), pip.get("local_index")
    return list(pip), None


def _local_pkg_fingerprint(path: str) -> str:
    """Content stamp for a local source package: walk of relative
    paths + sizes + mtimes. Without it, editing the package in place
    would serve the stale cached venv forever (the requirement STRING
    doesn't change)."""
    h = hashlib.sha1()
    for root, dirs, files in os.walk(path):
        # skip build artifacts: pip's source build writes egg-info/
        # build/ INTO the package dir, and including them would change
        # the key between staging and the worker's re-exec check
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git",
                                      "build", "dist")
                         and not d.endswith(".egg-info"))
        for fn in sorted(files):
            fp = os.path.join(root, fn)
            try:
                st = os.stat(fp)
            except OSError:
                continue
            h.update(f"{os.path.relpath(fp, path)}:"
                     f"{st.st_size}:{st.st_mtime_ns}".encode())
    return h.hexdigest()[:12]


def pip_env_dir(runtime_env: Dict[str, Any]) -> Optional[str]:
    pkgs, index = _pip_spec(runtime_env)
    if pkgs is None:
        return None
    import json
    keyed = []
    for p in sorted(pkgs):
        if os.path.isdir(p):       # local source dir: key by content
            keyed.append(f"{p}@{_local_pkg_fingerprint(p)}")
        else:
            keyed.append(p)
    key = hashlib.sha1(json.dumps([keyed, index])
                       .encode()).hexdigest()[:16]
    return os.path.join(_CACHE_DIR, "venvs", key)


def stage_pip_env(runtime_env: Dict[str, Any],
                  timeout_s: float = 600.0) -> Optional[str]:
    """Materialize the env's virtualenv on THIS node and return its
    python executable; cache-hit by requirements hash (reference:
    python/ray/_private/runtime_env/pip.py — per-URI venv cache built
    by the runtime-env agent on the executing node).

    The venv uses --system-site-packages so the framework stack (jax,
    numpy, ray_tpu's deps) stays importable, matching the reference's
    inherit-base-environment behavior. Installs run with --no-index
    unless a local_index is given — this image has no network, so pip
    envs install local wheels/source dirs (--no-build-isolation: the
    system setuptools does the build)."""
    pkgs, index = _pip_spec(runtime_env)
    if pkgs is None:
        return None
    vdir = pip_env_dir(runtime_env)
    py = os.path.join(vdir, "bin", "python")
    marker = os.path.join(vdir, ".ok")
    if os.path.exists(marker):
        return py                          # cache hit
    os.makedirs(os.path.dirname(vdir), exist_ok=True)
    lock = vdir + ".lock"
    import subprocess
    import time
    try:
        fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        # another process is staging this exact env: wait for it —
        # unless its recorded pid is dead (SIGKILLed staker), in
        # which case break the stale lock and take over.
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if os.path.exists(marker):
                return py
            if not os.path.exists(lock):    # staker finished/failed
                return stage_pip_env(runtime_env, timeout_s)
            try:
                with open(lock) as f:
                    owner = int(f.read().strip() or 0)
                if owner:
                    os.kill(owner, 0)       # raises if dead
            except (OSError, ValueError):
                try:
                    os.unlink(lock)         # dead owner: break it
                except OSError:
                    pass
                return stage_pip_env(runtime_env, timeout_s)
            time.sleep(0.25)
        raise TimeoutError(f"pip env {vdir} staging timed out")
    try:
        os.write(fd, str(os.getpid()).encode())
        os.close(fd)
        if not os.path.exists(py):
            proc = subprocess.run(
                [sys.executable, "-m", "venv",
                 "--system-site-packages", vdir],
                capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"venv creation failed (rc={proc.returncode}): "
                    f"{(proc.stderr or '')[-2000:]}")
            # --system-site-packages links the SYSTEM python's site
            # dir, but this interpreter may itself be a venv (the
            # image's /opt/venv) holding the whole framework stack —
            # layer OUR site-packages underneath via a .pth so jax/
            # numpy/setuptools stay importable (venv-local packages
            # still win: their dir sorts first on sys.path).
            own_sites = [p for p in sys.path
                         if p.endswith("site-packages")
                         and os.path.isdir(p)]
            with open(os.path.join(_venv_site(vdir),
                                   "_raytpu_base.pth"), "w") as f:
                f.write("\n".join(own_sites) + "\n")
        if pkgs:           # empty list = bare venv, nothing to install
            cmd = [py, "-m", "pip", "install",
                   "--no-warn-script-location",
                   "--no-build-isolation",
                   "--disable-pip-version-check"]
            if index:
                cmd += ["--no-index", "--find-links", index]
            else:
                cmd += ["--no-index"]
            cmd += pkgs
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout_s)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pip env install failed (rc={proc.returncode}): "
                    f"{(proc.stderr or '')[-2000:]}")
        with open(marker, "w") as f:
            f.write("ok")
        return py
    finally:
        try:
            os.unlink(lock)
        except OSError:
            pass


# --------------------------------------------------------------- conda envs
# Reference: python/ray/_private/runtime_env/conda.py — named envs
# resolve to their interpreter; dict specs materialize a cached env;
# workers re-exec under the env's python (same dedicated-worker
# routing as pip envs).

def find_conda() -> Optional[str]:
    import shutil
    for name in ("mamba", "micromamba", "conda"):
        p = shutil.which(name)
        if p:
            return p
    return None


def conda_available() -> bool:
    return find_conda() is not None


def _conda_env_dir(spec: Dict[str, Any]) -> str:
    key = hashlib.sha1(json.dumps(spec, sort_keys=True,
                                  default=str).encode()).hexdigest()[:16]
    return os.path.join(_CACHE_DIR, "conda", key)


def conda_env_python(runtime_env: Dict[str, Any],
                     timeout_s: float = 900.0) -> Optional[str]:
    """Resolve (or materialize) the env's conda environment and return
    its python executable. Named envs must already exist; dict specs
    create a cached env under the runtime-env cache (one `conda env
    create` per spec hash per node). Raises when no conda/mamba binary
    is on PATH — callers surface that through env_setup_failed."""
    spec = runtime_env.get("conda")
    if spec is None:
        return None
    exe = find_conda()
    if exe is None:
        raise RuntimeError(
            "runtime_env['conda'] requested but no conda/mamba/"
            "micromamba binary is on PATH on this node")
    import subprocess
    if isinstance(spec, str):
        if os.path.isdir(spec):              # prefix path
            return os.path.join(spec, "bin", "python")
        proc = subprocess.run([exe, "env", "list", "--json"],
                              capture_output=True, text=True,
                              timeout=60)
        envs = json.loads(proc.stdout or "{}").get("envs", [])
        for prefix in envs:
            if os.path.basename(prefix) == spec:
                return os.path.join(prefix, "bin", "python")
        raise RuntimeError(f"conda env {spec!r} not found on this "
                           f"node (known: {envs})")
    edir = _conda_env_dir(spec)
    py = os.path.join(edir, "bin", "python")

    def build():
        import tempfile
        with tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False) as f:
            json.dump(spec, f)
            spec_file = f.name
        try:
            proc = subprocess.run(
                [exe, "env", "create", "--prefix", edir,
                 "--file", spec_file, "--yes"],
                capture_output=True, text=True, timeout=timeout_s)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"conda env create failed (rc={proc.returncode}):"
                    f" {(proc.stderr or '')[-2000:]}")
        finally:
            os.unlink(spec_file)

    _locked_stage(edir, py, build, timeout_s)
    return py


def _locked_stage(target_dir: str, probe_path: str, build,
                  timeout_s: float) -> None:
    """Cross-process once-only staging: first claimer builds under a
    pid-stamped lock file; others wait for the .ok marker (or break a
    dead claimer's lock). Shared by conda staging (and any future
    cached-artifact env type)."""
    import time as _time
    marker = os.path.join(target_dir, ".raytpu_ok")
    if os.path.exists(marker):
        return
    os.makedirs(os.path.dirname(target_dir), exist_ok=True)
    lock = target_dir + ".lock"
    try:
        fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        deadline = _time.time() + timeout_s
        while _time.time() < deadline:
            if os.path.exists(marker):
                return
            if not os.path.exists(lock):
                return _locked_stage(target_dir, probe_path, build,
                                     timeout_s)
            try:
                with open(lock) as f:
                    owner = int(f.read().strip() or 0)
                if owner:
                    os.kill(owner, 0)
            except (OSError, ValueError):
                try:
                    os.unlink(lock)
                except OSError:
                    pass
                return _locked_stage(target_dir, probe_path, build,
                                     timeout_s)
            _time.sleep(0.25)
        raise TimeoutError(f"staging {target_dir} timed out")
    try:
        os.write(fd, str(os.getpid()).encode())
        os.close(fd)
        if not os.path.exists(probe_path):
            build()
        with open(marker, "w") as f:
            f.write("ok")
    finally:
        try:
            os.unlink(lock)
        except OSError:
            pass


# ---------------------------------------------------------- container envs
# Reference: python/ray/_private/runtime_env/container.py — the worker
# command is wrapped in `podman/docker run` with the node's state
# mounted. Here the node agent wraps spawn_worker_process's command
# when the env names an image; the prefix builder is a pure function
# so it is testable without an engine installed.

def find_container_engine() -> Optional[str]:
    import shutil
    for name in ("podman", "docker"):
        p = shutil.which(name)
        if p:
            return p
    return None


def container_command_prefix(runtime_env: Dict[str, Any],
                             engine: Optional[str] = None,
                             env_vars: Optional[Dict[str, str]] = None):
    """The argv prefix that runs a worker inside the env's image:
    host networking (the worker must reach the head's loopback RPC
    ports), host PID namespace (the worker's parent-death watcher
    probes the spawner's host pid), /dev/shm and the repo mounted
    through (the C++ store mapping and cwd imports must resolve to
    the same paths inside). `env_vars` become --env flags — they must
    sit BEFORE the image (everything after it is the in-container
    command). Returns None when the env has no container."""
    spec = (runtime_env or {}).get("container")
    if not spec:
        return None
    engine = engine or find_container_engine()
    if engine is None:
        raise RuntimeError(
            "runtime_env['container'] requested but neither podman "
            "nor docker is on PATH on this node")
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        "..", ".."))
    prefix = [engine, "run", "--rm", "-i",
              "--network", "host",
              "--ipc", "host",           # shm store segments
              "--pid", "host",           # parent-death watcher
              "-v", f"{repo}:{repo}",
              "-v", "/dev/shm:/dev/shm",
              "-w", repo]
    for k, v in (env_vars or {}).items():
        prefix += ["--env", f"{k}={v}"]
    for opt in spec.get("run_options", []):
        prefix.append(opt)
    prefix.append(spec["image"])
    return prefix


def _venv_site(vdir: str) -> str:
    v = sys.version_info
    return os.path.join(vdir, "lib", f"python{v.major}.{v.minor}",
                        "site-packages")


def pip_env_site_packages(runtime_env: Dict[str, Any]) -> Optional[str]:
    """The venv's site-packages dir (for in-process sys.path layering
    in the local runtime, where re-exec isn't possible)."""
    vdir = pip_env_dir(runtime_env)
    return None if vdir is None else _venv_site(vdir)


def _stage_working_dir(path: str) -> str:
    """Resolve a working_dir to a directory; .zip archives extract into
    a content-addressed cache (the URI-cache analogue)."""
    if not path.endswith(".zip"):
        if not os.path.isdir(path):
            raise FileNotFoundError(
                f"runtime_env working_dir {path!r} does not exist")
        return path
    with open(path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    target = os.path.join(_CACHE_DIR, digest)
    if not os.path.isdir(target):
        os.makedirs(_CACHE_DIR, exist_ok=True)
        tmp = target + ".tmp"
        with zipfile.ZipFile(path) as zf:
            zf.extractall(tmp)
        try:
            os.rename(tmp, target)
        except OSError:
            pass   # concurrent extraction won the race
    return target


@contextlib.contextmanager
def runtime_env_context(runtime_env: Optional[Dict[str, Any]]):
    """Apply a runtime_env around an execution, restoring afterwards.

    The lock is held only while mutating/restoring process-global state,
    NOT across user-code execution: an in-process task blocking on
    ``get()`` of another runtime_env task (the LocalRuntime runs tasks on
    threads in one process) must not deadlock the other task's apply
    step. The cost is that concurrent runtime_env tasks can observe each
    other's env between apply and restore — the docstring above already
    concedes env bleed for tasks *without* an env; true isolation is the
    env-keyed worker-process path in the multiprocess runtime.
    """
    if not runtime_env:
        yield
        return
    token = object()
    applied = {"env": False, "cwd": False, "paths": []}

    # pip env (in-process application): stage the venv OUTSIDE the
    # apply lock (installs take seconds) and layer its site-packages
    # onto sys.path. Dedicated env workers instead re-exec into the
    # venv interpreter at startup (worker_main) and skip this — the
    # marker env var says this process already IS that venv.
    pip_site = None
    if runtime_env.get("pip") is not None:
        vdir = pip_env_dir(runtime_env)
        if os.environ.get("RAY_TPU_VENV") != vdir:
            stage_pip_env(runtime_env)
            pip_site = pip_env_site_packages(runtime_env)

    def _apply_locked():
        if pip_site:
            _claim_path(pip_site)
            applied["paths"].append(pip_site)
        for k, v in (runtime_env.get("env_vars") or {}).items():
            _env_stacks.setdefault(k, []).append([token,
                                                  os.environ.get(k)])
            os.environ[k] = v
        applied["env"] = True
        wd = runtime_env.get("working_dir")
        if wd:
            staged = _stage_working_dir(wd)
            _cwd_stack.append([token, os.getcwd()])
            applied["cwd"] = True
            os.chdir(staged)
            _claim_path(staged)
            applied["paths"].append(staged)
        for mod in (runtime_env.get("py_modules") or []):
            mod = os.path.abspath(mod)
            _claim_path(mod)
            applied["paths"].append(mod)

    def _restore_locked():
        # Idempotent: every branch consumes its `applied` mark, so a
        # double call (apply-failure path + finally) is a no-op.
        for p in applied["paths"]:
            _release_path(p)
        applied["paths"] = []
        if applied["cwd"]:
            _stack_restore(_cwd_stack, token,
                           lambda old: os.chdir(old))
            applied["cwd"] = False
        if applied["env"]:
            for k in (runtime_env.get("env_vars") or {}):
                stack = _env_stacks.get(k)
                if not stack:
                    continue

                def setter(old, k=k):
                    if old is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = old
                _stack_restore(stack, token, setter)
                if not stack:
                    _env_stacks.pop(k, None)
            applied["env"] = False

    try:
        with _apply_lock:
            try:
                _apply_locked()
            except BaseException:
                _restore_locked()   # half-applied: undo before raising
                raise
        yield
    finally:
        with _apply_lock:
            _restore_locked()
