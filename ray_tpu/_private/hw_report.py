"""Per-node hardware/resource reporter.

Role parity with the reference's reporter agent
(dashboard/modules/reporter/reporter_agent.py — psutil snapshots per
node shipped with heartbeats and surfaced by the dashboard). TPU
metrics come from already-initialized jax backends only: probing
`jax.devices()` here could block on a wedged device tunnel, so a node
that never touched the TPU simply reports none.
"""
from __future__ import annotations

import os
import sys
import time
from typing import Any, Dict, Optional


def collect_hw_stats(store=None) -> Dict[str, Any]:
    """One snapshot of this node's hardware state; cheap enough to
    ride every heartbeat."""
    import psutil
    vm = psutil.virtual_memory()
    try:
        disk = psutil.disk_usage("/")
        disk_stats = {"total": disk.total, "used": disk.used,
                      "percent": disk.percent}
    except OSError:
        disk_stats = {}
    stats: Dict[str, Any] = {
        "ts": time.time(),
        # interval=None: non-blocking delta since the previous call
        # (the first call returns 0.0 — fine for a periodic reporter)
        "cpu_percent": psutil.cpu_percent(interval=None),
        "cpu_count": psutil.cpu_count(),
        "load_avg": list(os.getloadavg()),
        "mem": {"total": vm.total, "used": vm.used,
                "percent": vm.percent},
        "disk": disk_stats,
        "pid_count": len(psutil.pids()),
    }
    if store is not None:
        try:
            stats["object_store"] = store.stats()
        except Exception:
            pass
    tpu = _tpu_stats()
    if tpu:
        stats["tpu"] = tpu
    return stats


def _tpu_stats() -> Optional[list]:
    """Per-device HBM stats, ONLY if a jax TPU backend already exists
    in this process (never trigger device initialization here)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        from jax._src import xla_bridge
        if not xla_bridge._backends:        # nothing initialized yet
            return None
        out = []
        for dev in jax.local_devices():
            if dev.platform != "tpu":
                continue
            entry = {"id": dev.id, "kind": dev.device_kind}
            try:
                ms = dev.memory_stats() or {}
                entry["hbm_bytes_in_use"] = ms.get("bytes_in_use")
                entry["hbm_bytes_limit"] = ms.get("bytes_limit")
            except Exception:
                pass
            out.append(entry)
        return out or None
    except Exception:
        return None
