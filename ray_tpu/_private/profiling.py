"""Task timeline profiling.

Capability parity with the reference's profile-event pipeline
(src/ray/core_worker/profiling.h, python/ray/_private/profiling.py,
GlobalState.chrome_tracing_dump in python/ray/_private/state.py:413): every
runtime records named events/spans; ``timeline()`` dumps a Chrome
``chrome://tracing`` JSON. The TPU flavor can merge XLA profiler traces via
``merge_xla_trace``.
"""
from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []
_enabled = True
_open_spans: Dict[tuple, float] = {}


def set_enabled(flag: bool):
    global _enabled
    _enabled = flag


def clear():
    with _lock:
        _events.clear()
        _open_spans.clear()


def record(category: str, name: str, **meta):
    if not _enabled:
        return
    with _lock:
        _events.append({
            "cat": category, "name": name, "ph": "i",
            "ts": time.time() * 1e6,
            "pid": 0, "tid": threading.get_ident() % 100000,
            "args": meta or {},
        })


def record_span_start(category: str, name: str, key=None):
    if not _enabled:
        return
    with _lock:
        _open_spans[(category, name, key,
                     threading.get_ident())] = time.time() * 1e6


def record_span_end(category: str, name: str, key=None):
    if not _enabled:
        return
    tid = threading.get_ident()
    with _lock:
        start = _open_spans.pop((category, name, key, tid), None)
        if start is None:
            return
        now = time.time() * 1e6
        _events.append({
            "cat": category, "name": name, "ph": "X",
            "ts": start, "dur": now - start,
            "pid": 0, "tid": tid % 100000, "args": {},
        })


@contextmanager
def profile(name: str, category: str = "user"):
    record_span_start(category, name)
    try:
        yield
    finally:
        record_span_end(category, name)


def chrome_trace(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    with _lock:
        events = list(_events)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def merge_xla_trace(xla_trace_events: List[Dict[str, Any]]):
    """Merge device-side events from the XLA profiler into the host
    timeline (pid=1 lane)."""
    with _lock:
        for e in xla_trace_events:
            e = dict(e)
            e["pid"] = 1
            _events.append(e)
