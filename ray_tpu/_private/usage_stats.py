"""Usage stats: opt-out collection, written locally only.

Capability parity with the reference's usage_lib
(python/ray/_private/usage/usage_lib.py): collects a schema-stable
payload (version, API surface used, cluster shape) gated by an opt-out
env var — ON by default like the reference, opt out with
RAY_TPU_USAGE_STATS_ENABLED=0. This build has zero egress, so "report" writes the payload to a
local file instead of POSTing; the collection/gating logic is the part
with parity value.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List

_ENV_OPT_OUT = "RAY_TPU_USAGE_STATS_ENABLED"

_lock = threading.Lock()
_features_used: set = set()


def usage_stats_enabled() -> bool:
    # Mirrors RAY_usage_stats_enabled gating; default ON like the
    # reference (opt-out), but writing only to the local session dir.
    return os.environ.get(_ENV_OPT_OUT, "1").strip().lower() not in (
        "0", "false", "no", "off")


def record_library_usage(feature: str) -> None:
    if not usage_stats_enabled():
        return
    with _lock:
        _features_used.add(feature)


def get_features_used() -> List[str]:
    with _lock:
        return sorted(_features_used)


def build_payload() -> Dict[str, Any]:
    import ray_tpu
    payload: Dict[str, Any] = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "collected_at": time.time(),
        "libraries_used": get_features_used(),
    }
    try:
        payload["cluster_resources"] = ray_tpu.api.cluster_resources()
    except Exception:
        payload["cluster_resources"] = {}
    return payload


def report_usage(path: str = "/tmp/ray_tpu/usage_stats.json") -> str:
    """Writes the payload locally (no egress in this environment)."""
    if not usage_stats_enabled():
        return ""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(build_payload(), f, indent=2)
    return path
