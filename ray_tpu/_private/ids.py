"""Typed binary IDs.

Mirrors the *capability* of the reference's ID system
(src/ray/design_docs/id_specification.md, src/ray/common/id.h): fixed-width
binary IDs with structured derivation (object ids derive from the creating
task id + return index; actor ids embed the job id), hex round-trip, nil
sentinels. Implementation is fresh Python (the distributed runtime keeps the
same wire format).
"""
from __future__ import annotations

import itertools
import os
import struct
import threading

_JOB_ID_LEN = 4
_UNIQUE_LEN = 16          # task/actor/node unique part
_TASK_ID_LEN = _JOB_ID_LEN + _UNIQUE_LEN   # 20
_OBJECT_INDEX_LEN = 4
_OBJECT_ID_LEN = _TASK_ID_LEN + _OBJECT_INDEX_LEN  # 24


class _UniqueBytes:
    """Fast unique-byte generator: one urandom() per process (plus one
    per fork) for an 8-byte nonce, then an atomic counter. os.urandom
    per ID costs ~100 us of syscall on the hot submit path; this is
    ~1 us and still cluster-unique (nonce collision odds are the same
    as two random IDs colliding)."""

    def __init__(self):
        self._pid = -1
        self._lock = threading.Lock()

    def _reseed(self):
        self._nonce = os.urandom(8)
        self._counter = itertools.count(1)
        self._pid = os.getpid()

    def take(self, n: int) -> bytes:
        if self._pid != os.getpid():     # fresh process or fork
            with self._lock:
                if self._pid != os.getpid():
                    self._reseed()
        seq = struct.pack("<Q", next(self._counter))
        out = self._nonce + seq
        if n <= 16:
            return out[:n]
        # (nonce, seq) is already unique; zero-pad wider IDs.
        return out + b"\x00" * (n - 16)


_unique = _UniqueBytes()


class BaseID:
    SIZE = _UNIQUE_LEN
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, "
                f"got {len(binary)}")
        self._bytes = bytes(binary)
        self._hash = None

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_unique.take(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        # IDs key every hot-path dict (store entries, refcounts,
        # locations); cache the hash on first use.
        h = self._hash
        if h is None:
            h = self._hash = hash((type(self).__name__, self._bytes))
        return h

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"


class JobID(BaseID):
    SIZE = _JOB_ID_LEN
    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(cls.SIZE, "little"))

    @classmethod
    def next(cls) -> "JobID":
        with cls._lock:
            cls._counter += 1
            return cls.from_int(cls._counter)


class NodeID(BaseID):
    SIZE = _UNIQUE_LEN


class WorkerID(BaseID):
    SIZE = _UNIQUE_LEN


class ActorID(BaseID):
    SIZE = _JOB_ID_LEN + 12

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + _unique.take(12))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:_JOB_ID_LEN])


class TaskID(BaseID):
    SIZE = _TASK_ID_LEN

    @classmethod
    def of(cls, job_id: JobID) -> "TaskID":
        return cls(job_id.binary() + _unique.take(_UNIQUE_LEN))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:_JOB_ID_LEN])


class ObjectID(BaseID):
    SIZE = _OBJECT_ID_LEN

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() +
                   index.to_bytes(_OBJECT_INDEX_LEN, "little"))

    @classmethod
    def from_random(cls) -> "ObjectID":
        # A put() object: synthesize a fresh task id slot.
        return cls(_unique.take(_TASK_ID_LEN) +
                   (0).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_TASK_ID_LEN])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[_TASK_ID_LEN:], "little")


class PlacementGroupID(BaseID):
    SIZE = _UNIQUE_LEN


class GangID(BaseID):
    """ID of an SPMD mesh gang (no reference analogue; TPU-native concept)."""
    SIZE = _UNIQUE_LEN
