"""Central config/flag system.

Capability parity with the reference's RAY_CONFIG macro table
(src/ray/common/ray_config_def.h: typed defaults, env-var override
``RAY_<name>``, init-time ``_system_config`` dict override). Here flags are a
typed registry with ``RAY_TPU_<name>`` env override and
``init(_system_config={...})`` runtime override; the same table is exported to
native components via environment when worker processes are spawned.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(v: str) -> bool:
    return v.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}

# name -> (type, default, doc)
_CONFIG_DEFS: Dict[str, tuple] = {}


def define_flag(name: str, typ: type, default: Any, doc: str = "") -> None:
    _CONFIG_DEFS[name] = (typ, default, doc)


# --- Core runtime flags (analogues of ray_config_def.h entries) ------------
define_flag("max_direct_call_object_size", int, 100 * 1024,
            "Results <= this many serialized bytes are inlined into the "
            "caller's in-process store instead of the shared-memory store.")
define_flag("task_retry_delay_ms", int, 0,
            "Delay before the owner resubmits a failed task.")
define_flag("bulk_pull_threshold_bytes", int, 64 * 1024 * 1024,
            "Cross-node pulls at or above this size go through head "
            "pull-slot admission (reference: push_manager.h in-flight "
            "caps); smaller pulls run unthrottled.")
define_flag("bulk_pull_slots_per_source", int, 2,
            "Concurrent bulk pulls one replica serves before new "
            "pullers are told to back off.")
define_flag("transfer_prewarm_mb", int, 128,
            "Scratch bytes each node's transfer daemon moves through "
            "its own socket+arena path at startup (background): the "
            "first bulk receive of a cold process runs ~13x slower "
            "than steady state on shared hosts. Capped at 1/8 of the "
            "store; <16MB disables.")
define_flag("borrow_grace_s", float, 3.0,
            "Window the head waits after an escaped object's owner "
            "drop (or its last borrow drop) before freeing: covers "
            "refs pickled but not yet deserialized/registered by "
            "their receiver.")
define_flag("bulk_pull_global_slots", int, 2,
            "Cluster-wide cap on concurrent bulk pulls. On shared/"
            "virtualized hosts concurrent bulk memory traffic "
            "degrades superlinearly (originally 0.8s solo vs 28s x4 "
            "for a 1 GiB copy; reproduce on any host with "
            "tools/bench_broadcast_degradation.py), so transfers are "
            "serialized near the host's effective bandwidth; raise "
            "on real multi-host clusters where each node has its own "
            "memory bus.")
define_flag("default_max_retries", int, 3,
            "Default max_retries for normal tasks.")
define_flag("actor_restart_backoff_ms", int, 0,
            "Backoff before restarting a failed actor.")
define_flag("heartbeat_period_ms", int, 1000,
            "Node heartbeat period to the control plane.")
define_flag("num_heartbeats_timeout", int, 30,
            "Heartbeats missed before a node is marked dead.")
define_flag("object_store_memory_bytes", int, 2 * 1024 ** 3,
            "Capacity of the per-node shared-memory object store.")
define_flag("object_spill_threshold", float, 0.8,
            "Fill fraction of the object store above which primary copies "
            "spill to disk.")
define_flag("object_spill_dir", str, "/tmp/ray_tpu_spill",
            "Directory for spilled objects.")
define_flag("worker_pool_prestart", bool, True,
            "Prestart workers based on scheduling backlog.")
define_flag("env_worker_idle_timeout_s", float, 60.0,
            "Idle seconds before a dedicated runtime-env worker "
            "process is reaped (worker_pool idle reaping analogue).")
define_flag("max_pending_actor_calls", int, 10000,
            "Client-side cap on in-flight calls per actor handle.")
define_flag("memory_monitor_threshold", float, 0.0,
            "Node memory used-fraction above which task dispatch pauses "
            "(0 disables; analogue of memory_monitor in the raylet).")
define_flag("memory_monitor_interval_ms", int, 250,
            "Memory monitor poll interval.")
define_flag("testing_delay_us_max", int, 0,
            "Chaos: max random delay injected into every runtime event "
            "handler (analogue of testing_asio_delay_us).")
define_flag("testing_delay_us_min", int, 0,
            "Chaos: min random delay for event handlers.")
define_flag("enable_timeline", bool, True,
            "Record per-task profile events for the timeline dump.")
define_flag("scheduler_spread_threshold", float, 0.5,
            "Hybrid policy: below this node utilization prefer packing "
            "on the local node; above it spread.")
define_flag("lineage_max_bytes", int, 64 * 1024 * 1024,
            "Cap on lineage kept for object reconstruction.")
define_flag("gang_restart_max_attempts", int, 3,
            "Max gang restarts for SPMD mesh actors before giving up.")
define_flag("mesh_checkpoint_interval_s", float, 600.0,
            "Default async-checkpoint interval for gang fault tolerance.")
define_flag("dcn_axis_name", str, "dcn",
            "Mesh axis name used for the cross-slice (DCN) dimension.")
define_flag("log_dir", str, "/tmp/ray_tpu/session_latest/logs",
            "Per-session log directory.")
define_flag("metrics_export_port", int, 0,
            "Prometheus export port (0 = disabled).")
define_flag("cluster_token", str, "",
            "Shared secret authenticating every RPC connection "
            "(redis-password analogue). Auto-generated by the node "
            "manager and propagated to child processes; set "
            "RAY_TPU_cluster_token to attach an external driver. "
            "Empty = auth disabled (standalone/manual setups).")


def ensure_cluster_token() -> str:
    """Generate the cluster secret at the root of a process tree (the
    node manager) if none is configured; children inherit it via
    to_env()/os.environ."""
    tok = GlobalConfig.cluster_token
    if not tok:
        import secrets
        tok = secrets.token_hex(16)
        GlobalConfig.apply_system_config({"cluster_token": tok})
        os.environ[_ENV_PREFIX + "cluster_token"] = tok
    return tok


class _Config:
    """Singleton flag store with env + runtime overrides."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values: Dict[str, Any] = {}
        self._load_defaults()

    def _load_defaults(self):
        for name, (typ, default, _doc) in _CONFIG_DEFS.items():
            env = os.environ.get(_ENV_PREFIX + name)
            if env is not None:
                self._values[name] = _PARSERS[typ](env)
            else:
                self._values[name] = default

    def get(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise KeyError(f"Unknown config flag: {name}") from None

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)

    def apply_system_config(self, overrides: Dict[str, Any]) -> None:
        """Runtime override, the ``ray.init(_system_config=...)`` analogue."""
        with self._lock:
            for name, value in overrides.items():
                if name not in _CONFIG_DEFS:
                    raise KeyError(f"Unknown config flag: {name}")
                typ = _CONFIG_DEFS[name][0]
                if isinstance(value, str) and typ is not str:
                    value = _PARSERS[typ](value)
                if not isinstance(value, typ):
                    # bool is an int subclass; order of checks handles it.
                    raise TypeError(
                        f"Flag {name} expects {typ.__name__}, "
                        f"got {type(value).__name__}")
                self._values[name] = value

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._values)

    def to_env(self) -> Dict[str, str]:
        """Serialize non-default flags for child worker processes."""
        out = {}
        for name, (typ, default, _doc) in _CONFIG_DEFS.items():
            v = self._values[name]
            if v != default:
                out[_ENV_PREFIX + name] = json.dumps(v) if typ not in (
                    str,) else v
        return out

    def reset(self):
        with self._lock:
            self._values.clear()
            self._load_defaults()


GlobalConfig = _Config()


def chaos_delay():
    """Shared chaos hook: random delay injected into runtime event
    handlers (N22, common/asio/asio_chaos.cc analogue). Controlled by
    the testing_delay_us_{min,max} flags."""
    hi = GlobalConfig.testing_delay_us_max
    if hi:
        import random
        import time
        lo = GlobalConfig.testing_delay_us_min
        time.sleep(random.uniform(lo, hi) / 1e6)
