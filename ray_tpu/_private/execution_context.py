"""Thread-local execution context shared across module identities.

worker_main runs as BOTH `__main__` (the spawned process) and
`ray_tpu.runtime.worker_main` (imports from other code): a
module-level threading.local defined there would exist twice. This
tiny neutral module holds the one true context object; worker_main
writes it, ray_tpu.runtime_context reads it.
"""
import threading

task_ctx = threading.local()
