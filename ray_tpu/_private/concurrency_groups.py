"""Actor concurrency groups: shared mailbox routing (reference:
concurrency groups in the core worker task transports — per-group
parallelism, FIFO within a group, independent across groups).

Both actor executors (the in-process runtime's _ActorState and the
worker process's _ActorSlot) delegate their group bookkeeping here so
routing/sizing/sentinel logic cannot drift between runtimes."""
from __future__ import annotations

import queue
from typing import Dict, Optional

DEFAULT_GROUP = "_default"


class GroupMailboxes:
    """One FIFO mailbox per concurrency group (+ the default group,
    which carries the actor's max_concurrency)."""

    def __init__(self, concurrency_groups: Optional[Dict[str, int]],
                 max_concurrency: int):
        self.groups: Dict[str, int] = dict(concurrency_groups or {})
        self.max_concurrency = max(1, max_concurrency)
        self.boxes: Dict[str, "queue.Queue"] = {
            g: queue.Queue() for g in [DEFAULT_GROUP, *self.groups]}

    def size(self, group: str) -> int:
        if group == DEFAULT_GROUP:
            return self.max_concurrency
        return max(1, self.groups[group])

    def route(self, group: Optional[str]) -> "queue.Queue":
        """Mailbox for a call's group; raises ValueError on an
        undeclared group."""
        g = group or DEFAULT_GROUP
        box = self.boxes.get(g)
        if box is None:
            raise ValueError(
                f"actor has no concurrency group {g!r} "
                f"(declared: {sorted(self.groups) or 'none'})")
        return box

    def items(self):
        return self.boxes.items()

    def stop(self):
        """One sentinel per consumer thread of every group."""
        for g, box in self.boxes.items():
            for _ in range(self.size(g)):
                box.put(None)

    def stop_one_per_group(self):
        """One sentinel per group (async pumps: one pump per group)."""
        for box in self.boxes.values():
            box.put(None)
