"""ctypes binding for the C++ shared-memory object store
(src/object_store/shm_store.cc — the plasma-equivalent host-RAM tier).

The library is built on demand with g++ (no pybind11 in the image; the
C ABI + ctypes keeps the binding dependency-free). Zero-copy reads: get()
returns a memoryview into the shm mapping; put/get of numpy arrays never
copy through Python byte strings on the read side.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Optional, Tuple

from ray_tpu._private.ids import ObjectID

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC = os.path.join(_REPO_ROOT, "src", "object_store", "shm_store.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LIB = os.path.join(_BUILD_DIR, "libshm_store.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

SHM_OK = 0
SHM_ERR_EXISTS = -1
SHM_ERR_NOT_FOUND = -2
SHM_ERR_FULL = -3
SHM_ERR_TOO_MANY = -7

_ERRORS = {
    -1: "object already exists",
    -2: "object not found",
    -3: "store full (after eviction)",
    -4: "invalid object state",
    -5: "timeout",
    -6: "system error",
    -7: "too many objects",
}


class ShmStoreError(RuntimeError):
    def __init__(self, code: int, op: str):
        self.code = code
        self.op = op
        super().__init__(f"shm_store.{op}: "
                         f"{_ERRORS.get(code, f'error {code}')}")

    def __reduce__(self):
        # Default exception pickling replays __init__ with args=(msg,)
        # — wrong arity for this two-arg signature, so a worker's
        # ShmStoreError would morph into a TypeError on the driver.
        return (type(self), (self.code, self.op))


class ShmTimeout(ShmStoreError):
    pass


def _ensure_built() -> str:
    if not os.path.exists(_LIB) or \
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-Wall", "-fPIC", "-std=c++17", "-shared",
             "-o", _LIB, _SRC, "-lpthread", "-lrt"],
            check=True, capture_output=True)
    return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_ensure_built())
        lib.store_create.restype = ctypes.c_void_p
        lib.store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.store_attach.restype = ctypes.c_void_p
        lib.store_attach.argtypes = [ctypes.c_char_p]
        lib.store_detach.argtypes = [ctypes.c_void_p]
        lib.store_destroy.argtypes = [ctypes.c_void_p]
        lib.store_create_object.restype = ctypes.c_int64
        lib.store_create_object.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.store_create_object_ex.restype = ctypes.c_int64
        lib.store_create_object_ex.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_int]
        lib.store_lru_candidate.restype = ctypes.c_int
        lib.store_lru_candidate.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        lib.store_seal.restype = ctypes.c_int
        lib.store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_get.restype = ctypes.c_int
        lib.store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.store_release.restype = ctypes.c_int
        lib.store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_delete.restype = ctypes.c_int
        lib.store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_contains.restype = ctypes.c_int
        lib.store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_stats.argtypes = [
            ctypes.c_void_p] + [ctypes.POINTER(ctypes.c_uint64)] * 4
        lib.store_base.restype = ctypes.c_void_p
        lib.store_base.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def _check(code: int, op: str):
    if code == SHM_OK:
        return
    if code == -5:
        raise ShmTimeout(code, op)
    raise ShmStoreError(code, op)


class _PinnedExporter:
    """Buffer-protocol owner of one read pin on a sealed object.

    memoryview(_PinnedExporter(...)) re-exports the shm mapping; every
    derived slice / numpy array keeps THIS object alive through the
    buffer chain (PEP 688 __buffer__), and the pin (store refcount) is
    released exactly once when the last reference dies. store_delete
    refuses refcount>0 entries, so pinned pages can never be reused
    under a live view (the plasma client-mapping safety contract,
    plasma/store.h:55)."""

    __slots__ = ("_store", "_oid", "_view", "_released", "__weakref__")

    def __init__(self, store, oid, view):
        self._store = store
        self._oid = oid
        self._view = view
        self._released = False

    def __buffer__(self, flags):
        return memoryview(self._view)

    def __len__(self):
        return len(self._view)

    def __del__(self):
        if not self._released:
            self._released = True
            try:
                self._store.release(self._oid)
            except Exception:
                pass    # store torn down first (interpreter exit)


class ShmObjectStore:
    """One node-local store segment. The node runtime calls create();
    workers attach() by name."""

    def __init__(self, handle: int, name: str, owner: bool):
        self._lib = _load()
        self._h = handle
        self.name = name
        self._owner = owner
        base = self._lib.store_base(self._h)
        self._base = base
        # Spill directory is derived from the store name so every
        # process attached to the same segment agrees on it (reference:
        # N15 object spilling, raylet/local_object_manager.h:38 +
        # _private/external_storage.py filesystem backend).
        from ray_tpu._private.config import GlobalConfig
        self._spill_dir = os.path.join(
            GlobalConfig.object_spill_dir, name.lstrip("/"))
        self._num_spilled = 0
        self._num_restored = 0

    # --- lifecycle --------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int) -> "ShmObjectStore":
        lib = _load()
        h = lib.store_create(name.encode(), capacity)
        if not h:
            raise ShmStoreError(-6, "create")
        return cls(h, name, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmObjectStore":
        lib = _load()
        h = lib.store_attach(name.encode())
        if not h:
            raise ShmStoreError(-2, "attach")
        return cls(h, name, owner=False)

    def close(self):
        if self._h:
            if self._owner:
                self._lib.store_destroy(self._h)
                import shutil
                shutil.rmtree(self._spill_dir, ignore_errors=True)
            else:
                self._lib.store_detach(self._h)
            self._h = None

    # --- object lifecycle -------------------------------------------------

    def put_bytes(self, oid: ObjectID, data: bytes) -> None:
        self.put_parts(oid, [data], len(data))

    def put_parts(self, oid: ObjectID, parts, total: int) -> None:
        """Create + stream buffer-like parts straight into the shm
        mapping + seal. With serialization.serialize_parts this is the
        single-copy put path (reference: plasma CreateAndSeal writes
        the serialized object directly into the store buffer).

        No-evict create: under memory pressure cold LRU objects are
        spilled to disk to make room (never silently dropped); if the
        incoming object still doesn't fit, it spills itself."""
        while True:
            off = self._lib.store_create_object_ex(
                self._h, oid.binary(), total, 0)
            if off == SHM_ERR_FULL:
                if self._spill_lru_one():
                    continue
                self._spill_parts(oid, parts)
                return
            if off == SHM_ERR_TOO_MANY:
                self._spill_parts(oid, parts)
                return
            if off < 0:
                _check(int(off), "create_object")
            break
        dst = (ctypes.c_char * total).from_address(self._base + off)
        view = memoryview(dst).cast("B")
        pos = 0
        for p in parts:
            if isinstance(p, memoryview):
                p = p.cast("B")
            n = len(p)
            view[pos:pos + n] = p
            pos += n
        _check(self._lib.store_seal(self._h, oid.binary()), "seal")

    # --- raw create/seal (streamed remote pulls) ---------------------------

    def create_for_write(self, oid: ObjectID, size: int) -> Optional[
            memoryview]:
        """Allocate an unsealed object and return a writable view into
        the mapping, or None if it cannot fit (caller falls back to a
        buffered pull + spill). Readers block until seal_raw()."""
        while True:
            off = self._lib.store_create_object_ex(
                self._h, oid.binary(), size, 0)
            if off == SHM_ERR_FULL:
                if self._spill_lru_one():
                    continue
                return None
            if off in (SHM_ERR_TOO_MANY, SHM_ERR_EXISTS):
                return None
            if off < 0:
                _check(int(off), "create_object")
            dst = (ctypes.c_char * size).from_address(self._base + off)
            return memoryview(dst).cast("B")

    def seal_raw(self, oid: ObjectID) -> None:
        _check(self._lib.store_seal(self._h, oid.binary()), "seal")

    def abort_raw(self, oid: ObjectID) -> None:
        """Drop an unsealed allocation after a failed streamed write."""
        try:
            self._lib.store_delete(self._h, oid.binary())
        except Exception:
            pass

    def _spill_lru_one(self) -> bool:
        """Spill+delete the LRU sealed refcount-0 object. False if no
        candidate exists."""
        buf = ctypes.create_string_buffer(len(ObjectID.nil().binary()))
        rc = self._lib.store_lru_candidate(self._h, buf)
        if rc != SHM_OK:
            return False
        victim = ObjectID(buf.raw)
        return self.spill(victim)

    # --- spilling ---------------------------------------------------------

    def _spill_path(self, oid: ObjectID) -> str:
        return os.path.join(self._spill_dir, oid.hex())

    def _spill_bytes(self, oid: ObjectID, data: bytes) -> None:
        self._spill_parts(oid, [data])

    def _spill_parts(self, oid: ObjectID, parts) -> None:
        os.makedirs(self._spill_dir, exist_ok=True)
        path = self._spill_path(oid)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            for p in parts:
                f.write(p)
        os.replace(tmp, path)   # atomic: readers see whole objects only
        self._num_spilled += 1

    def _read_spilled(self, oid: ObjectID) -> Optional[bytes]:
        try:
            with open(self._spill_path(oid), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def spill(self, oid: ObjectID) -> bool:
        """Explicitly move a sealed object from shm to disk."""
        try:
            data = self.get_bytes_shm_only(oid, timeout_ms=0)
        except ShmStoreError:
            return False
        self._spill_bytes(oid, data)
        try:
            # shm copy only — the spill file IS the object now.
            _check(self._lib.store_delete(self._h, oid.binary()),
                   "delete")
        except ShmStoreError:
            pass
        return True

    def restore(self, oid: ObjectID) -> bool:
        """Try to bring a spilled object back into shm."""
        data = self._read_spilled(oid)
        if data is None:
            return False
        off = self._lib.store_create_object_ex(self._h, oid.binary(),
                                               len(data), 0)
        if off < 0:
            return off == SHM_ERR_EXISTS
        ctypes.memmove(self._base + off, data, len(data))
        _check(self._lib.store_seal(self._h, oid.binary()), "seal")
        self._num_restored += 1
        try:
            os.unlink(self._spill_path(oid))   # shm copy is primary now
        except OSError:
            pass
        return True

    def get_view(self, oid: ObjectID,
                 timeout_ms: int = -1) -> memoryview:
        """Zero-copy view; caller must release(oid) when done."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        _check(self._lib.store_get(self._h, oid.binary(), timeout_ms,
                                   ctypes.byref(off), ctypes.byref(size)),
               "get")
        buf = (ctypes.c_char * size.value).from_address(
            self._base + off.value)
        return memoryview(buf)

    def get_bytes_shm_only(self, oid: ObjectID,
                           timeout_ms: int = -1) -> bytes:
        view = self.get_view(oid, timeout_ms)
        try:
            return bytes(view)
        finally:
            self.release(oid)

    # Objects at or above this size are returned as PINNED shm views
    # instead of heap copies (get_blob): on the 1-core rig a 1 GiB
    # heap copy costs ~1s alone and SECONDS under process concurrency
    # (the host throttles concurrent bulk memory traffic superlinearly
    # — measured 0.8s solo vs 6s x2 vs 28s x4), and the reference's
    # plasma contract is zero-copy reads anyway (ray_object.h:28).
    PIN_THRESHOLD = 1 << 20

    def get_blob(self, oid: ObjectID, timeout_ms: int = -1):
        """Zero-copy get: large sealed objects return a READ-ONLY
        memoryview whose exporter holds the store pin — the object's
        pages stay mapped and unevictable until every derived view
        (including numpy arrays deserialized over it) is GC'd.
        Small objects and spill-resident objects return bytes.
        Blocking + spill-fallback semantics match get_bytes."""
        deadline = None if timeout_ms < 0 else \
            time.monotonic() + timeout_ms / 1000.0
        slice_cap = 250   # re-check the spill dir only on slice expiry
        first = True
        while True:
            slice_ms = 0 if first else (
                slice_cap if deadline is None else
                max(0, min(slice_cap,
                           int((deadline - time.monotonic()) * 1000))))
            first = False
            view = None
            try:
                view = self.get_view(oid, timeout_ms=slice_ms)
            except ShmTimeout:
                pass
            except ShmStoreError as e:
                if e.code not in (-2, -4):
                    raise
            if view is not None:
                if len(view) < self.PIN_THRESHOLD:
                    try:
                        return bytes(view)
                    finally:
                        self.release(oid)
                return memoryview(
                    _PinnedExporter(self, oid, view)).toreadonly()
            data = self._read_spilled(oid)
            if data is not None:
                return data
            if deadline is not None and time.monotonic() >= deadline:
                raise ShmTimeout(-5, "get")

    def get_bytes(self, oid: ObjectID, timeout_ms: int = -1) -> bytes:
        """Get with spill fallback: poll shm in slices, checking the
        spill directory between slices (a spilled object never signals
        the shm condvar)."""
        deadline = None if timeout_ms < 0 else \
            time.monotonic() + timeout_ms / 1000.0
        # Probe shm first (0-timeout): resident objects — the common
        # case — never pay a disk syscall.
        try:
            return self.get_bytes_shm_only(oid, timeout_ms=0)
        except ShmStoreError:
            pass
        data = self._read_spilled(oid)
        if data is not None:
            return data
        slice_cap = 250   # re-check the spill dir only on slice expiry
        while True:
            slice_ms = slice_cap if deadline is None else \
                max(0, min(slice_cap,
                           int((deadline - time.monotonic()) * 1000)))
            try:
                return self.get_bytes_shm_only(oid, timeout_ms=slice_ms)
            except ShmTimeout:
                pass
            except ShmStoreError as e:
                # 0-slice probes report not-found/unsealed, not timeout.
                if e.code not in (-2, -4):
                    raise
            data = self._read_spilled(oid)
            if data is not None:
                return data
            if deadline is not None and time.monotonic() >= deadline:
                raise ShmTimeout(-5, "get")

    def release(self, oid: ObjectID):
        self._lib.store_release(self._h, oid.binary())
        # Deferred delete: a delete() that arrived while this process
        # held read pins completes at the last release (the plasma
        # delete-on-release contract). Cross-process pins degrade to
        # LRU eviction once the refcount drops — never a leak, just
        # lazier reclamation.
        deferred = getattr(self, "_deferred_deletes", None)
        if deferred and oid in deferred:
            rc = self._lib.store_delete(self._h, oid.binary())
            if rc in (SHM_OK, SHM_ERR_NOT_FOUND):
                deferred.discard(oid)

    def delete(self, oid: ObjectID):
        had_spill = False
        try:
            os.unlink(self._spill_path(oid))
            had_spill = True
        except OSError:
            pass
        rc = self._lib.store_delete(self._h, oid.binary())
        if had_spill and rc == SHM_ERR_NOT_FOUND:
            return   # spilled-only object: the unlink was the delete
        if rc == -4:
            # Pinned by live views (zero-copy gets): defer to the
            # last release in this process; other processes' pins
            # leave a refcount-0 entry for LRU once dropped.
            deferred = getattr(self, "_deferred_deletes", None)
            if deferred is None:
                deferred = self._deferred_deletes = set()
            deferred.add(oid)
            return
        _check(rc, "delete")

    def contains(self, oid: ObjectID) -> bool:
        if self._lib.store_contains(self._h, oid.binary()):
            return True
        return os.path.exists(self._spill_path(oid))

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(4)]
        self._lib.store_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {"bytes_in_use": vals[0].value,
                "num_objects": vals[1].value,
                "num_evictions": vals[2].value,
                "capacity": vals[3].value,
                "num_spilled": self._num_spilled,
                "num_restored": self._num_restored}

    # --- serialization-aware helpers --------------------------------------

    def put_object(self, oid: ObjectID, value) -> None:
        from ray_tpu._private import serialization
        self.put_bytes(oid, serialization.dumps(value))

    def get_object(self, oid: ObjectID, timeout_ms: int = -1):
        from ray_tpu._private import serialization
        return serialization.loads(self.get_bytes(oid, timeout_ms))
