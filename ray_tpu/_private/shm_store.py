"""ctypes binding for the C++ shared-memory object store
(src/object_store/shm_store.cc — the plasma-equivalent host-RAM tier).

The library is built on demand with g++ (no pybind11 in the image; the
C ABI + ctypes keeps the binding dependency-free). Zero-copy reads: get()
returns a memoryview into the shm mapping; put/get of numpy arrays never
copy through Python byte strings on the read side.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

from ray_tpu._private.ids import ObjectID

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC = os.path.join(_REPO_ROOT, "src", "object_store", "shm_store.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LIB = os.path.join(_BUILD_DIR, "libshm_store.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

SHM_OK = 0
_ERRORS = {
    -1: "object already exists",
    -2: "object not found",
    -3: "store full (after eviction)",
    -4: "invalid object state",
    -5: "timeout",
    -6: "system error",
    -7: "too many objects",
}


class ShmStoreError(RuntimeError):
    def __init__(self, code: int, op: str):
        self.code = code
        super().__init__(f"shm_store.{op}: "
                         f"{_ERRORS.get(code, f'error {code}')}")


class ShmTimeout(ShmStoreError):
    pass


def _ensure_built() -> str:
    if not os.path.exists(_LIB) or \
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-Wall", "-fPIC", "-std=c++17", "-shared",
             "-o", _LIB, _SRC, "-lpthread", "-lrt"],
            check=True, capture_output=True)
    return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_ensure_built())
        lib.store_create.restype = ctypes.c_void_p
        lib.store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.store_attach.restype = ctypes.c_void_p
        lib.store_attach.argtypes = [ctypes.c_char_p]
        lib.store_detach.argtypes = [ctypes.c_void_p]
        lib.store_destroy.argtypes = [ctypes.c_void_p]
        lib.store_create_object.restype = ctypes.c_int64
        lib.store_create_object.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.store_seal.restype = ctypes.c_int
        lib.store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_get.restype = ctypes.c_int
        lib.store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.store_release.restype = ctypes.c_int
        lib.store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_delete.restype = ctypes.c_int
        lib.store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_contains.restype = ctypes.c_int
        lib.store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_stats.argtypes = [
            ctypes.c_void_p] + [ctypes.POINTER(ctypes.c_uint64)] * 4
        lib.store_base.restype = ctypes.c_void_p
        lib.store_base.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def _check(code: int, op: str):
    if code == SHM_OK:
        return
    if code == -5:
        raise ShmTimeout(code, op)
    raise ShmStoreError(code, op)


class ShmObjectStore:
    """One node-local store segment. The node runtime calls create();
    workers attach() by name."""

    def __init__(self, handle: int, name: str, owner: bool):
        self._lib = _load()
        self._h = handle
        self.name = name
        self._owner = owner
        base = self._lib.store_base(self._h)
        self._base = base

    # --- lifecycle --------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int) -> "ShmObjectStore":
        lib = _load()
        h = lib.store_create(name.encode(), capacity)
        if not h:
            raise ShmStoreError(-6, "create")
        return cls(h, name, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmObjectStore":
        lib = _load()
        h = lib.store_attach(name.encode())
        if not h:
            raise ShmStoreError(-2, "attach")
        return cls(h, name, owner=False)

    def close(self):
        if self._h:
            if self._owner:
                self._lib.store_destroy(self._h)
            else:
                self._lib.store_detach(self._h)
            self._h = None

    # --- object lifecycle -------------------------------------------------

    def put_bytes(self, oid: ObjectID, data: bytes) -> None:
        off = self._lib.store_create_object(self._h, oid.binary(),
                                            len(data))
        if off < 0:
            _check(int(off), "create_object")
        ctypes.memmove(self._base + off, data, len(data))
        _check(self._lib.store_seal(self._h, oid.binary()), "seal")

    def get_view(self, oid: ObjectID,
                 timeout_ms: int = -1) -> memoryview:
        """Zero-copy view; caller must release(oid) when done."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        _check(self._lib.store_get(self._h, oid.binary(), timeout_ms,
                                   ctypes.byref(off), ctypes.byref(size)),
               "get")
        buf = (ctypes.c_char * size.value).from_address(
            self._base + off.value)
        return memoryview(buf)

    def get_bytes(self, oid: ObjectID, timeout_ms: int = -1) -> bytes:
        view = self.get_view(oid, timeout_ms)
        try:
            return bytes(view)
        finally:
            self.release(oid)

    def release(self, oid: ObjectID):
        self._lib.store_release(self._h, oid.binary())

    def delete(self, oid: ObjectID):
        _check(self._lib.store_delete(self._h, oid.binary()), "delete")

    def contains(self, oid: ObjectID) -> bool:
        return bool(self._lib.store_contains(self._h, oid.binary()))

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(4)]
        self._lib.store_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {"bytes_in_use": vals[0].value,
                "num_objects": vals[1].value,
                "num_evictions": vals[2].value,
                "capacity": vals[3].value}

    # --- serialization-aware helpers --------------------------------------

    def put_object(self, oid: ObjectID, value) -> None:
        from ray_tpu._private import serialization
        self.put_bytes(oid, serialization.dumps(value))

    def get_object(self, oid: ObjectID, timeout_ms: int = -1):
        from ray_tpu._private import serialization
        return serialization.loads(self.get_bytes(oid, timeout_ms))
