"""Pure chunked-prefill + decode step planner for the LLM engine.

One scheduling round of the continuous-batching engine
(serve/engine.py) is planned here, device-free: given a host-side
snapshot of the slots, decide (a) which mid-prefill slots advance and
by how many prompt tokens, under a shared per-round token budget
(``prefill_budget``, the ``prefill_chunk`` knob), and (b) how many
decode steps to dispatch in the SAME round. The engine dispatches the
prefill chunk first and the decode chunk immediately behind it, both
asynchronously, so the device pipeline interleaves
``P D P D P D ...`` — decode never stalls for a whole prompt the way
monolithic padded-batch prefill stalls it (the r05 161ms-TTFT /
1.63x-throughput shape this module exists to fix).

Pure and deterministic on purpose: tier-1 CPU tests drive
``plan_step`` directly with synthetic ``SlotView`` snapshots and
assert the interleaving/budget/run-ahead properties without touching
a device (the same reason the reference keeps its scheduling policy
separate from its raylet I/O).

Policy, in order:

- Prefill grants: mid-prefill slots in lane-then-admission order
  (online lane first, FIFO within each lane — admission never
  reorders within a lane, so neither does prefill) each receive
  ``min(prompt_remaining, budget_left)`` tokens until the round's
  token budget or the prefill batch width runs out. A long prompt
  takes the whole budget for several rounds; several short prompts
  pack into one round. ``prompt_remaining`` is net of any tokens the
  prefix cache (serve/prefix_cache.py) satisfied at admission — a
  cache-hit slot enters mid-prompt, so the round's budget only ever
  pays for tokens actually computed; skipped prefix tokens never
  consume it.
- Decode steps: if any seeded slot exists, decode rides every round.
  While admission work is pending (a free slot, an unseeded slot, a
  prefill grant this round) the cadence stays at ``decode_chunk`` so
  new arrivals join promptly and prefill chunks interleave; with a
  full, fully-seeded batch the plan runs ahead to the next completion
  event (min owed over riders) exactly as before. With an eos the
  run-ahead is bounded — tokens past an unpredicted eos are wasted.
  Under the engine's OVERLAPPED loop the views may trail the device
  frontier (``SlotView.stale``: dispatched-but-undrained steps); any
  stale eos-bounded rider tightens the cap to one ``decode_chunk``,
  which bounds the worst-case discard on a late-revealed eos to one
  chunk per slot.
- Priority lanes (``SlotView.batch``, serve/batch_tier.py): offline
  batch slots share the round with online traffic but never crowd it.
  Prefill grants order ONLINE slots first (FIFO within the lane),
  batch slots take whatever budget is left — a deep batch backlog can
  never delay an online prompt's next chunk by more than the chunk
  already in flight. Decode is lane-blind by design: a seeded batch
  slot rides the same dispatch as everyone else (evicting it saves
  nothing once its KV is resident — preemption happens in the engine
  when pages or slots are actually contended, batch-first).
- Spec lane (``spec_enabled``, serve/spec_decode.py): when any seeded
  slot carries draft tokens this round, ONE batched verify dispatch
  replaces the decode chunk — every seeded slot rides it (a slot with
  zero drafts degrades to a plain one-token step inside the same
  dispatch), so speculation never forks the device schedule. Draft
  counts are clamped so a verify can never emit past a slot's
  remaining budget (``owed``) nor past ``max_run_ahead`` (spec rounds
  count against the same run-ahead ceiling as decode). When NO slot
  has a proposal the round degrades to the plain decode lane — held
  to quick cadence, since running ahead would decode past every
  future proposal window — and the prefill lane is computed first
  either way: speculation never starves chunked prefill.
"""
from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

# Device-count-agnosticism CONTRACT, test-enforced
# (tests/test_scheduler_guard.py): the planner may import nothing
# beyond this list — in particular never jax / jaxlib / numpy — and
# never reads device topology. One StepPlan must drive a 1-chip engine
# and an N-way tensor-parallel engine identically; the moment a device
# count leaks in here, sharded and unsharded replicas plan different
# rounds and token parity dies.
ALLOWED_IMPORTS = frozenset({"__future__", "dataclasses", "typing"})

# Priority lanes: every request carries one of these through
# admission, planning, and preemption. ONLINE is the latency-critical
# default; BATCH marks preemptible offline work (serve/batch_tier.py)
# that soaks idle capacity and yields it slot-by-slot the moment
# online traffic arrives.
LANE_ONLINE = "online"
LANE_BATCH = "batch"

# Replica roles for prefill/decode disaggregation
# (serve/engine_pool.py). UNIFIED is the classic mixed replica;
# PREFILL replicas take new prompts and hand finished prefills to the
# decode pool over the KV-migration path; DECODE replicas own the
# token streams after handoff. Pure data: the role changes nothing in
# ``plan_step`` itself — it only selects the knob clamps below, which
# the engine applies to the arguments it passes in.
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_UNIFIED = "unified"
REPLICA_ROLES = frozenset({ROLE_PREFILL, ROLE_DECODE, ROLE_UNIFIED})


def role_plan_caps(role, *, page_size, decode_chunk, prefill_budget,
                   max_run_ahead):
    """Role-adjusted planner knobs, pure data in -> data out.

    - ``prefill``: refuses decode-phase growth. Run-ahead is clamped
      to one decode chunk so a prefill replica never commits long
      decode dispatches: its steady state is prompt chunks plus the
      single bridging token each handoff needs, and anything longer
      only delays the next waiting prompt (exactly the interference
      disaggregation exists to remove).
    - ``decode``: skips the prefill lane. The per-round prefill
      budget collapses to one page plus one token — enough to absorb
      a handoff's residual tail (``len(prompt) mod page_size`` plus
      the bridging token always fits one round) and to crawl through
      a full plain prefill when a fallback or chaos resubmit lands
      here (correct, just slow — a hard refusal would strand exactly
      the recovery paths that must keep working).
    - ``unified``: knobs pass through untouched.

    Unknown roles raise: a typo'd role silently planning as unified
    would erase the disaggregation it was meant to configure.
    """
    if role not in REPLICA_ROLES:
        raise ValueError(
            f"unknown replica role {role!r}; expected one of "
            f"{sorted(REPLICA_ROLES)}")
    caps = {"prefill_budget": prefill_budget,
            "max_run_ahead": max_run_ahead}
    if role == ROLE_PREFILL:
        caps["max_run_ahead"] = max(1, min(max_run_ahead,
                                           decode_chunk))
    elif role == ROLE_DECODE:
        caps["prefill_budget"] = max(1, min(prefill_budget,
                                            page_size + 1))
    return caps

# Named knob presets for the two serving regimes. Pure data (the
# import guard above applies): the engine/deployment layer maps these
# onto its constructor knobs; the planner itself reads nothing here.
#
# - ``latency``: the defaults the online path has always run —
#   short decode cadence, bounded admission queue, moderate prefill
#   chunks so TTFT stays flat under interleave.
# - ``throughput``: offline batch inference with no TTFT SLO — deep
#   (unbounded) admission queue, large prefill chunks so prompt
#   processing amortizes dispatch overhead, longer decode run-ahead.
#   ``max_queued=None`` is deliberate: the batch driver bounds its own
#   in-flight window (serve/batch_tier.py), so the engine queue depth
#   is the driver's concurrency knob, not a shed boundary.
SCHEDULER_PROFILES = {
    "latency": {
        "decode_chunk": 4,
        "prefill_chunk": 256,
        "max_run_ahead": 256,
        "max_queued": 2,
    },
    "throughput": {
        "decode_chunk": 16,
        "prefill_chunk": 512,
        "max_run_ahead": 512,
        "max_queued": None,
    },
}


def scheduler_profile(name):
    """Knob preset for ``name`` ('latency' | 'throughput'): a fresh
    dict the caller may mutate. Unknown names raise — a silently
    defaulted profile would hide a typo'd deployment config."""
    try:
        return dict(SCHEDULER_PROFILES[name])
    except KeyError:
        raise ValueError(
            f"unknown scheduler profile {name!r}; expected one of "
            f"{sorted(SCHEDULER_PROFILES)}") from None


@dataclasses.dataclass(frozen=True)
class SlotView:
    """Host snapshot of one occupied slot, as the planner sees it."""
    sid: int                 # slot index
    admit_seq: int           # admission order (FIFO fairness)
    prompt_remaining: int    # prompt tokens not yet prefilled
    owed: int                # decode steps still owed (seeded slots)
    seeded: bool             # riding decode dispatches already
    spec_drafts: int = 0     # draft tokens proposed this round
                             # (prompt-lookup, serve/spec_decode.py)
    stale: int = 0           # decode steps dispatched but not yet
                             # read back: under the engine's
                             # overlapped loop the view may TRAIL the
                             # device frontier by up to one round —
                             # this is the depth of that trail. 0
                             # under the lockstep loop (the pre-plan
                             # drain settles everything).
    pulling: bool = False    # PULLING phase: a cross-replica KV
                             # prefix pull is in flight for this slot
                             # (serve/kv_migration.py). It holds the
                             # slot so admission order is preserved,
                             # but must receive NO prefill grant —
                             # its prompt either lands from the pull
                             # or requeues for plain prefill. Unseeded
                             # by construction, so the quick-cadence
                             # rule already treats it as pending
                             # admission work.
    batch: bool = False      # BATCH lane (priority=LANE_BATCH):
                             # preemptible offline work. Prefill
                             # grants order online slots first; the
                             # engine preempts batch slots before any
                             # online slot when pages or slots run
                             # dry.

    @property
    def prefilling(self) -> bool:
        return self.prompt_remaining > 0 and not self.pulling


@dataclasses.dataclass(frozen=True)
class PrefillGrant:
    sid: int
    tokens: int


@dataclasses.dataclass(frozen=True)
class SpecGrant:
    """One slot's ride on this round's batched verify dispatch.
    ``drafts`` is the number of proposed tokens to verify — 0 means
    the slot rides as a plain one-token step (its row is just
    [cur])."""
    sid: int
    drafts: int


@dataclasses.dataclass(frozen=True)
class StepPlan:
    prefill: Tuple[PrefillGrant, ...]
    decode_steps: int
    spec: Tuple[SpecGrant, ...] = ()

    @property
    def idle(self) -> bool:
        return (not self.prefill and self.decode_steps == 0
                and not self.spec)


def plan_step(slots: Sequence[SlotView], *, total_slots: int,
              prefill_budget: int, decode_chunk: int,
              max_run_ahead: int, prefill_batch: int,
              eos_bounded: bool,
              spec_enabled: bool = False) -> StepPlan:
    """Plan one scheduling round. Pure: no device, no clock, no
    engine state — everything it needs is in the arguments.

    slots: occupied slots only (free slots are ``total_slots`` minus
    ``len(slots)``). Returns the prefill grants (FIFO, budget-packed)
    and either the decode step count (0 = no decode dispatch) or, when
    ``spec_enabled`` and any seeded slot proposed drafts, the spec
    grants for one batched verify dispatch (decode_steps is then 0 —
    the lanes are exclusive per round).
    """
    if prefill_budget < 1:
        raise ValueError("prefill_budget must be >= 1")
    if decode_chunk < 1:
        raise ValueError("decode_chunk must be >= 1")

    grants = []
    budget = prefill_budget
    # Lane-ordered prefill: every online slot (FIFO) ahead of every
    # batch slot (FIFO) — a deep batch backlog mid-prefill must never
    # consume the budget an online prompt's next chunk needs. bool
    # sorts False < True, so (batch, admit_seq) is exactly that order.
    for v in sorted((v for v in slots if v.prefilling),
                    key=lambda v: (v.batch, v.admit_seq)):
        if budget <= 0 or len(grants) >= prefill_batch:
            break
        take = min(v.prompt_remaining, budget)
        grants.append(PrefillGrant(v.sid, take))
        budget -= take

    seeded = sorted((v for v in slots if v.seeded),
                    key=lambda v: v.admit_seq)
    if not seeded:
        return StepPlan(tuple(grants), 0)

    if spec_enabled and any(v.spec_drafts > 0 for v in seeded):
        # Spec lane: ONE batched verify covering every seeded slot
        # (zero-draft rows are plain one-token steps), replacing this
        # round's decode chunk. A verify emits between 1 and
        # drafts + 1 tokens per slot, so drafts are clamped to the
        # slot's remaining budget minus the guaranteed bonus token
        # and to the run-ahead ceiling the decode lane honors.
        spec = tuple(
            SpecGrant(v.sid, max(0, min(v.spec_drafts, v.owed - 1,
                                        max_run_ahead - 1)))
            for v in seeded)
        return StepPlan(tuple(grants), 0, spec)

    # Defensive clamp: cancelled/expired slots are torn down before
    # the engine snapshots views, so they never appear here at all —
    # but an eos-mode rider's owed can still arrive negative (decoded
    # past budget while emission trails) and must not drag min(rem)
    # below the 1-step floor.
    rem = [max(0, v.owed) for v in seeded]
    quick = (len(slots) < total_slots
             or any(not v.seeded for v in slots)
             or bool(grants))
    # Spec mode keeps the decode lane on quick cadence even with a
    # full batch: run-ahead would decode past every future proposal
    # window before the host proposer gets another round (speculation
    # trades run-ahead pipelining for multi-token dispatches).
    steps = (decode_chunk if quick or spec_enabled
             else max(decode_chunk, min(rem)))
    if eos_bounded:
        steps = min(steps, 2 * decode_chunk)
        if any(v.stale > 0 for v in seeded):
            # Stale-frontier discard bound (overlapped loop): a rider
            # with undrained steps may already be past its eos
            # without the host knowing. Capping the next dispatch at
            # ONE decode chunk — together with the engine's trailing
            # drain, which blocks once the pipeline is two dispatches
            # deep — bounds the tokens ever discarded on a
            # late-revealed eos to at most one decode chunk per slot.
            steps = min(steps, decode_chunk)
    return StepPlan(tuple(grants), max(1, min(steps, max_run_ahead)))
