"""Pure chunked-prefill + decode step planner for the LLM engine.

One scheduling round of the continuous-batching engine
(serve/engine.py) is planned here, device-free: given a host-side
snapshot of the slots, decide (a) which mid-prefill slots advance and
by how many prompt tokens, under a shared per-round token budget
(``prefill_budget``, the ``prefill_chunk`` knob), and (b) how many
decode steps to dispatch in the SAME round. The engine dispatches the
prefill chunk first and the decode chunk immediately behind it, both
asynchronously, so the device pipeline interleaves
``P D P D P D ...`` — decode never stalls for a whole prompt the way
monolithic padded-batch prefill stalls it (the r05 161ms-TTFT /
1.63x-throughput shape this module exists to fix).

Pure and deterministic on purpose: tier-1 CPU tests drive
``plan_step`` directly with synthetic ``SlotView`` snapshots and
assert the interleaving/budget/run-ahead properties without touching
a device (the same reason the reference keeps its scheduling policy
separate from its raylet I/O).

Policy, in order:

- Prefill grants: mid-prefill slots in admission order (FIFO —
  admission never reorders, so neither does prefill) each receive
  ``min(prompt_remaining, budget_left)`` tokens until the round's
  token budget or the prefill batch width runs out. A long prompt
  takes the whole budget for several rounds; several short prompts
  pack into one round. ``prompt_remaining`` is net of any tokens the
  prefix cache (serve/prefix_cache.py) satisfied at admission — a
  cache-hit slot enters mid-prompt, so the round's budget only ever
  pays for tokens actually computed; skipped prefix tokens never
  consume it.
- Decode steps: if any seeded slot exists, decode rides every round.
  While admission work is pending (a free slot, an unseeded slot, a
  prefill grant this round) the cadence stays at ``decode_chunk`` so
  new arrivals join promptly and prefill chunks interleave; with a
  full, fully-seeded batch the plan runs ahead to the next completion
  event (min owed over riders) exactly as before. With an eos the
  run-ahead is bounded — tokens past an unpredicted eos are wasted.
"""
from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class SlotView:
    """Host snapshot of one occupied slot, as the planner sees it."""
    sid: int                 # slot index
    admit_seq: int           # admission order (FIFO fairness)
    prompt_remaining: int    # prompt tokens not yet prefilled
    owed: int                # decode steps still owed (seeded slots)
    seeded: bool             # riding decode dispatches already

    @property
    def prefilling(self) -> bool:
        return self.prompt_remaining > 0


@dataclasses.dataclass(frozen=True)
class PrefillGrant:
    sid: int
    tokens: int


@dataclasses.dataclass(frozen=True)
class StepPlan:
    prefill: Tuple[PrefillGrant, ...]
    decode_steps: int

    @property
    def idle(self) -> bool:
        return not self.prefill and self.decode_steps == 0


def plan_step(slots: Sequence[SlotView], *, total_slots: int,
              prefill_budget: int, decode_chunk: int,
              max_run_ahead: int, prefill_batch: int,
              eos_bounded: bool) -> StepPlan:
    """Plan one scheduling round. Pure: no device, no clock, no
    engine state — everything it needs is in the arguments.

    slots: occupied slots only (free slots are ``total_slots`` minus
    ``len(slots)``). Returns the prefill grants (FIFO, budget-packed)
    and the decode step count for this round (0 = no decode dispatch).
    """
    if prefill_budget < 1:
        raise ValueError("prefill_budget must be >= 1")
    if decode_chunk < 1:
        raise ValueError("decode_chunk must be >= 1")

    grants = []
    budget = prefill_budget
    for v in sorted((v for v in slots if v.prefilling),
                    key=lambda v: v.admit_seq):
        if budget <= 0 or len(grants) >= prefill_batch:
            break
        take = min(v.prompt_remaining, budget)
        grants.append(PrefillGrant(v.sid, take))
        budget -= take

    rem = [v.owed for v in slots if v.seeded]
    if not rem:
        return StepPlan(tuple(grants), 0)
    quick = (len(slots) < total_slots
             or any(not v.seeded for v in slots)
             or bool(grants))
    steps = decode_chunk if quick else max(decode_chunk, min(rem))
    if eos_bounded:
        steps = min(steps, 2 * decode_chunk)
    return StepPlan(tuple(grants), max(1, min(steps, max_run_ahead)))
