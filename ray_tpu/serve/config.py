"""Serve configs (reference: python/ray/serve/config.py pydantic schemas —
plain dataclasses here)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Request-driven autoscaling (reference:
    serve/_private/autoscaling_policy.py BasicAutoscalingPolicy)."""
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    # TPU-native: replicas can be SPMD mesh gangs.
    mesh: Optional[Dict[str, int]] = None
    # Live-reconfigurable options delivered to instance.reconfigure()
    # without restarting replicas (reference: deployment user_config +
    # rolling reconfigure, serve/_private/deployment_state.py).
    user_config: Optional[Dict[str, Any]] = None
    health_check_period_s: float = 5.0
    graceful_shutdown_timeout_s: float = 10.0
