"""Typed request-lifecycle errors for the LLM serving stack.

A production serving path treats the request lifecycle — abort,
timeout, shed, isolate — as part of its contract, which means the
FAILURE TYPES are part of the API: the HTTP proxy maps them to
status codes (429/504/499), clients branch on them, and tests assert
them. They live in this jax-free module so the proxy and client code
can import them without dragging the engine's device stack in.

Hierarchy (all subclass ``RequestError`` so existing ``except
RequestError`` call sites keep working):

- ``RequestCancelled``  — the client aborted (``RequestHandle.
  cancel()`` or a disconnect detected upstream). HTTP: 499-style.
- ``DeadlineExceeded``  — the request's ``deadline_s`` elapsed before
  completion (at any phase: queued, mid-prefill, decoding,
  mid-speculation). HTTP: 504.
- ``EngineOverloaded``  — bounded admission shed the request at
  ``submit`` because ``max_queued`` was reached. Fast failure is the
  point: the alternative is silent TTFT collapse as the queue grows
  without bound. Carries ``retry_after_s``. HTTP: 429 + Retry-After.
- ``EngineShutdown``    — the engine stopped while the request was
  queued or in flight; consumers are unblocked instead of hanging.
"""
from __future__ import annotations


class RequestError(Exception):
    """Base class for engine request failures."""


class RequestCancelled(RequestError):
    """The request was aborted by the client (cancel/disconnect)."""


class DeadlineExceeded(RequestError):
    """The request's deadline elapsed before it completed."""


class EngineOverloaded(RequestError):
    """Admission queue full: the request was shed, not queued.

    ``retry_after_s`` is the engine's hint for when capacity is
    likely back (the proxy surfaces it as a Retry-After header)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class EngineShutdown(RequestError):
    """The engine stopped while the request was queued/in flight."""


class PoolDegraded(EngineShutdown):
    """The replica pool hit its crash-loop restart cap: one or more
    replicas died repeatedly, automatic rebuilding stopped for them,
    and no healthy replica remains to take the request. Distinct from
    a plain ``EngineShutdown`` so operators (and tests) can tell "the
    pool was stopped" from "the pool burned through its restart
    budget" — the latter needs a human or an autoscaler, not a retry.
    HTTP: 503 (inherits ``EngineShutdown`` classification), plus
    Retry-After when the pool can estimate a restart/provisioning ETA
    (``retry_after_s``; None = no honest hint, bare 503)."""

    def __init__(self, msg: str,
                 retry_after_s: "float | None" = None):
        super().__init__(msg)
        if retry_after_s is not None:
            self.retry_after_s = float(retry_after_s)


class EngineDraining(RequestError):
    """The replica is draining (finishing in-flight work before a
    restart) and admits nothing new. Routers skip draining replicas,
    so a client only sees this when talking to a replica directly.
    HTTP: 503 — retry lands on a healthy replica."""


def classify_http_status(exc: BaseException) -> int:
    """Map an exception (possibly wrapped by the remote-call layer:
    ``TaskError.cause`` / ``__cause__`` chains, or stringly re-raised)
    to the lifecycle HTTP status. 500 when it is none of ours.

    Matching is BY NAME along the cause chain, not isinstance: the
    exception may have crossed a process boundary and been rebuilt by
    a different import of this module, or be a remote-traceback
    wrapper whose string carries the type name.
    """
    status_by_name = {
        "EngineOverloaded": 429,
        "DeadlineExceeded": 504,
        "GetTimeoutError": 504,
        "EngineShutdown": 503,
        "PoolDegraded": 503,
        "EngineDraining": 503,
        "RequestCancelled": 499,
    }
    seen = set()
    stack = [exc]
    while stack:
        e = stack.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        name = type(e).__name__
        if name in status_by_name:
            return status_by_name[name]
        stack.extend([getattr(e, "cause", None), e.__cause__,
                      e.__context__])
    # last resort: a stringly-wrapped remote error still names the type
    msg = str(exc)
    for name, status in status_by_name.items():
        if name in msg:
            return status
    return 500


def retry_after_s(exc: BaseException, default: float = 1.0) -> float:
    """Best-effort Retry-After extraction across wrapping layers.

    Takes the MAX over every hint found along the cause chain, not the
    first: a pool-aggregate ``EngineOverloaded`` chains the last
    per-replica shed as its ``__cause__``, and an honest Retry-After
    must cover the slowest replica, not whichever wrapper the walker
    happened to visit first."""
    seen = set()
    stack = [exc]
    best = None
    while stack:
        e = stack.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        v = getattr(e, "retry_after_s", None)
        if isinstance(v, (int, float)):
            best = float(v) if best is None else max(best, float(v))
        stack.extend([getattr(e, "cause", None), e.__cause__,
                      e.__context__])
    return default if best is None else best
