"""Progress watchdog for the EnginePool: detect wedged replicas and
escalate hang -> death.

The pool's availability story (PR 5) only triggers when a replica
RAISES: at-most-once token-identical resubmit rides the exception out
of a dead engine's ``_fail_all``. A replica that wedges SILENTLY — a
deadlocked dispatch, a stuck host<->device transfer, an XLA call that
never returns — raises nothing: it keeps its HEALTHY state, keeps
attracting prefix-affinity traffic, and strands every request it
holds until per-request deadlines fire one by one. Ray detects
liveness (heartbeats, ``num_heartbeats_timeout``) instead of
inferring it from silence; PR 7 built the training-side mirror
(``worker_progress_deadline_s``). This module closes the serving
side.

Signal: every engine carries a PROGRESS heartbeat (``LLMEngine._hb``)
touched lock-free at the top of each scheduling round, at every
dispatch completion, and at every readback drain — so a
long-but-moving prefill keeps it fresh while a wedge lets it go
stale. ``load_report()`` exposes ``heartbeat_age_s`` + ``has_work``
and deliberately works WITHOUT the engine lock (brief try, then
lock-free reads), so it doubles as the probe: it returns even from an
engine whose scheduler thread is parked holding the lock, and the
watchdog judges PROGRESS (heartbeat advanced / work drained), never
responsiveness.

Escalation ladder, per replica, driven by ``tick()``:

1. HEALTHY, heartbeat stale past ``stall_deadline_s/2`` WITH work
   pending -> **SUSPECT** (``pool.mark_suspect``). Routing, capacity
   counts, and the autoscaler's signals all skip a SUSPECT replica
   immediately — a maybe-dead replica must not count as capacity.
   An idle engine parks on its condition variable with a stale
   heartbeat and NO work: never suspected.
2. SUSPECT, probe shows progress (heartbeat advanced since the
   suspicion, or the work drained) -> back to **HEALTHY**
   (``pool.clear_suspect``). False alarms cost a few routing skips,
   nothing else.
3. SUSPECT, still silent at ``stall_deadline_s`` -> **WEDGED**
   (``pool.mark_wedged``): the engine is force-killed OUT-OF-BAND
   (lock-free — the wedged thread holds the engine lock, so the
   graceful path would deadlock) and the EXISTING death path runs:
   consumers unblock typed, unstreamed requests resubmit
   token-identically to survivors, the pool marks the replica DEAD
   and rebuilds it with a generation bump. The zombie step thread
   that later wakes finds itself fenced (``_force_killed``): it
   cannot commit tokens, cannot dispatch, cannot touch the prefix
   cache.

Healthy replicas are never probed into restarts: the watchdog only
ever acts on the one stale replica, and every transition re-checks
identity + state under the pool lock.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.serve import obs
from ray_tpu.serve.engine_pool import HEALTHY, SUSPECT
from ray_tpu.serve.errors import EngineShutdown


class ReplicaWedged(EngineShutdown):
    """The watchdog declared this request's replica wedged (no
    scheduler progress past ``stall_deadline_s``) and force-killed
    it. Subclasses ``EngineShutdown`` so the pool handle's recovery
    path treats it exactly like any other replica death: unstreamed
    requests resubmit, partially-streamed ones fail typed.

    ``bundle_path`` carries the flight-recorder bundle the watchdog
    dumped BEFORE the kill (None when recording is disabled or the
    dump failed) — the postmortem travels with the escalation."""

    bundle_path: Optional[str] = None


class PoolWatchdog:
    """Monitors an ``EnginePool``'s replicas for scheduler progress
    and escalates silence: SUSPECT at half the deadline, WEDGED (->
    force-kill -> death path) at ``stall_deadline_s``.

    Parameters
    ----------
    pool: the EnginePool to watch. Construction attaches the
        watchdog (``pool_stats()`` grows a ``watchdog`` block) but
        does NOT start the loop — call ``run()`` or drive ``tick()``
        manually (tests use a fake ``time_fn``).
    stall_deadline_s: silence budget. A replica with work pending
        and no heartbeat movement for this long is declared wedged.
    suspect_after_s: quarantine threshold (default: half the
        deadline). Must leave room for at least one probe between
        SUSPECT and WEDGED.
    poll_interval_s: tick cadence of ``run()`` (default: an eighth
        of the deadline, floored at 10ms) — several probes fit
        inside the deadline, so detection lands WITHIN it.
    time_fn: injectable clock (fake-clock policy tests).
    flight_dir: flight-recorder output directory. A WEDGED
        escalation dumps a postmortem bundle of the dying replica
        HERE *before* the force-kill (the engine's ring and counters
        are still intact, and the probe is lock-free so the wedged
        scheduler thread holding the engine lock cannot deadlock
        it), then attaches the bundle path to the ``ReplicaWedged``
        it raises and to the ``wedged`` log entry. Defaults to
        ``obs.default_flight_dir()``; pass ``flight_dir=False`` to
        disable recording.
    """

    def __init__(self, pool, *, stall_deadline_s: float = 5.0,
                 suspect_after_s: Optional[float] = None,
                 poll_interval_s: Optional[float] = None,
                 time_fn: Callable[[], float] = time.monotonic,
                 flight_dir: Any = None):
        if stall_deadline_s <= 0:
            raise ValueError("stall_deadline_s must be > 0")
        self.pool = pool
        self.stall_deadline_s = float(stall_deadline_s)
        self.suspect_after_s = (float(suspect_after_s)
                                if suspect_after_s is not None
                                else self.stall_deadline_s / 2)
        if not 0 < self.suspect_after_s <= self.stall_deadline_s:
            raise ValueError(
                "suspect_after_s must be in (0, stall_deadline_s]")
        self.poll_interval_s = (float(poll_interval_s)
                                if poll_interval_s is not None
                                else max(0.01,
                                         self.stall_deadline_s / 8))
        if flight_dir is None:
            flight_dir = obs.default_flight_dir()
        self.flight_dir: Optional[str] = flight_dir or None
        self._time = time_fn
        self._lock = threading.Lock()
        # idx -> (replica object, heartbeat age when suspected):
        # identity pins the suspicion to THIS incarnation — a rebuilt
        # replica at the same index starts clean
        self._suspects: Dict[int, tuple] = {}
        self.counts: Dict[str, int] = {
            "ticks": 0, "suspected": 0, "recovered": 0, "wedged": 0}
        self.log: List[Dict[str, Any]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        pool._watchdog = self

    # ------------------------------------------------------------ tick

    def tick(self) -> None:
        """One watchdog pass over every HEALTHY/SUSPECT replica."""
        with self._lock:
            self.counts["ticks"] += 1
        if getattr(self.pool, "_stopped", False):
            return
        with self.pool._lock:
            reps = [r for r in self.pool._replicas
                    if r.state in (HEALTHY, SUSPECT)]
        live_idxs = set()
        for rep in reps:
            live_idxs.add(rep.idx)
            try:
                rpt = rep.engine.load_report()
            except Exception:
                continue     # a failing probe is not progress, but
                             # the heartbeat judges — try next tick
            if rpt.get("stopped"):
                # died idle since the last route — same corpse
                # detection routing does
                self.pool._note_replica_death(rep)
                self._forget(rep.idx)
                continue
            hb_age = rpt.get("heartbeat_age_s")
            has_work = rpt.get("has_work")
            if hb_age is None or has_work is None:
                continue     # engine without the heartbeat surface
            if rep.state == HEALTHY:
                if has_work and hb_age >= self.suspect_after_s:
                    if self.pool.mark_suspect(rep):
                        with self._lock:
                            self._suspects[rep.idx] = (rep, hb_age)
                            self.counts["suspected"] += 1
                        self._log("suspect", rep, hb_age)
                continue
            # SUSPECT: probe verdict. Progress = the heartbeat moved
            # (its age shrank vs. what we recorded — it only grows
            # while wedged) or the work drained away.
            with self._lock:
                tracked = self._suspects.get(rep.idx)
            if tracked is None or tracked[0] is not rep:
                # suspected by a previous watchdog incarnation, or
                # tracking lost: adopt it now and judge next tick
                with self._lock:
                    self._suspects[rep.idx] = (rep, hb_age)
                continue
            suspected_hb_age = tracked[1]
            if not has_work or hb_age < suspected_hb_age:
                if self.pool.clear_suspect(rep):
                    with self._lock:
                        self.counts["recovered"] += 1
                    self._log("recovered", rep, hb_age)
                self._forget(rep.idx)
                continue
            if hb_age >= self.stall_deadline_s:
                err = ReplicaWedged(
                    f"replica {rep.idx} wedged: no scheduler "
                    f"progress for {hb_age:.2f}s "
                    f"(stall deadline {self.stall_deadline_s}s); "
                    f"force-killed by the watchdog")
                # Flight recorder BEFORE the kill: the wedged
                # engine's event ring / counters are still intact,
                # and the probe is lock-free, so this cannot hang
                # on the lock the stuck scheduler thread holds.
                err.bundle_path = self._record_flight(rep, hb_age)
                if self.pool.mark_wedged(rep, err,
                                         stalled_for_s=hb_age):
                    with self._lock:
                        self.counts["wedged"] += 1
                    self._log("wedged", rep, hb_age,
                              bundle=err.bundle_path)
                self._forget(rep.idx)
        # drop tracking for replicas that left the HEALTHY/SUSPECT
        # set behind our back (drained, killed, replaced)
        with self._lock:
            for idx in [i for i in self._suspects
                        if i not in live_idxs]:
                del self._suspects[idx]

    def _forget(self, idx: int) -> None:
        with self._lock:
            self._suspects.pop(idx, None)

    def _record_flight(self, rep, hb_age: float) -> Optional[str]:
        """Dump a postmortem bundle for ``rep`` (best-effort: a
        recorder failure must never block the escalation)."""
        if self.flight_dir is None:
            return None
        try:
            return obs.dump_flight_bundle(
                self.flight_dir, f"wedged-r{rep.idx}",
                engine=rep.engine, pool=self.pool, watchdog=self,
                extra={"replica": rep.idx,
                       "generation": rep.generation,
                       "heartbeat_age_s": round(hb_age, 4),
                       "stall_deadline_s": self.stall_deadline_s})
        except Exception:
            return None

    def _log(self, event: str, rep, hb_age: float, **extra) -> None:
        entry = {"event": event, "replica": rep.idx,
                 "generation": rep.generation,
                 "heartbeat_age_s": round(hb_age, 4),
                 "t": self._time()}
        entry.update(extra)
        self.log.append(entry)

    # ------------------------------------------------------ lifecycle

    def run(self, interval_s: Optional[float] = None
            ) -> "PoolWatchdog":
        """Start the watch loop in a daemon thread."""
        if self._thread is None:
            self._stop.clear()
            interval = (float(interval_s) if interval_s is not None
                        else self.poll_interval_s)

            def loop():
                while not self._stop.is_set():
                    try:
                        self.tick()
                    except Exception:
                        pass   # a broken tick must not kill the loop
                    self._stop.wait(interval)

            self._thread = threading.Thread(
                target=loop, name="pool-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None

    def stats(self) -> Dict[str, Any]:
        """The ``watchdog`` block in ``pool_stats()`` / artifacts."""
        with self._lock:
            out = dict(self.counts)
            out["active_suspects"] = len(self._suspects)
        out["stall_deadline_s"] = self.stall_deadline_s
        out["suspect_after_s"] = self.suspect_after_s
        out["poll_interval_s"] = self.poll_interval_s
        return out


class AgentWatchdog:
    """Agent-local watchdog: the same progress ladder as
    ``PoolWatchdog``, scoped to the ONE engine a ``ReplicaAgent``
    owns. In the fleet split the pool-side watchdog cannot see across
    the process boundary, so each agent watches its own engine and
    REPORTS the verdict outward: on a wedge it dumps the flight
    bundle, force-kills the engine (same out-of-band, lock-free kill)
    and invokes ``on_wedge(err)`` — the agent flags ``wedged=True``
    on its next lease renewal so the directory (and through it the
    router) learns of the wedge without ever probing the engine."""

    def __init__(self, get_engine: Callable[[], Any],
                 on_wedge: Callable[[BaseException], None], *,
                 stall_deadline_s: float = 5.0,
                 poll_interval_s: Optional[float] = None,
                 time_fn: Callable[[], float] = time.monotonic,
                 flight_dir: Any = None):
        if stall_deadline_s <= 0:
            raise ValueError("stall_deadline_s must be > 0")
        self._get_engine = get_engine
        self._on_wedge = on_wedge
        self.stall_deadline_s = float(stall_deadline_s)
        self.poll_interval_s = (float(poll_interval_s)
                                if poll_interval_s is not None
                                else max(0.01,
                                         self.stall_deadline_s / 8))
        if flight_dir is None:
            flight_dir = obs.default_flight_dir()
        self.flight_dir: Optional[str] = flight_dir or None
        self._time = time_fn
        self.counts: Dict[str, int] = {"ticks": 0, "wedged": 0}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def tick(self) -> Optional[ReplicaWedged]:
        """One probe; returns the escalation when it fires."""
        self.counts["ticks"] += 1
        eng = self._get_engine()
        if eng is None or getattr(eng, "_stopped", False):
            return None
        try:
            rpt = eng.load_report()
        except Exception:
            return None
        hb_age = rpt.get("heartbeat_age_s")
        if (not rpt.get("has_work") or hb_age is None
                or hb_age < self.stall_deadline_s):
            return None
        err = ReplicaWedged(
            f"agent engine wedged: no scheduler progress for "
            f"{hb_age:.2f}s (stall deadline "
            f"{self.stall_deadline_s}s); force-killed by the agent "
            f"watchdog")
        if self.flight_dir is not None:
            try:
                err.bundle_path = obs.dump_flight_bundle(
                    self.flight_dir, "wedged-agent", engine=eng,
                    extra={"heartbeat_age_s": round(hb_age, 4),
                           "stall_deadline_s":
                               self.stall_deadline_s})
            except Exception:
                err.bundle_path = None
        try:
            eng.force_kill(err)
        except Exception:
            pass
        self.counts["wedged"] += 1
        try:
            self._on_wedge(err)
        except Exception:
            pass
        return err

    def run(self, interval_s: Optional[float] = None
            ) -> "AgentWatchdog":
        if self._thread is None:
            self._stop.clear()
            interval = (float(interval_s) if interval_s is not None
                        else self.poll_interval_s)

            def loop():
                while not self._stop.is_set():
                    try:
                        self.tick()
                    except Exception:
                        pass
                    self._stop.wait(interval)

            self._thread = threading.Thread(
                target=loop, name="agent-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None

    def stats(self) -> Dict[str, Any]:
        out = dict(self.counts)
        out["stall_deadline_s"] = self.stall_deadline_s
        out["poll_interval_s"] = self.poll_interval_s
        return out
