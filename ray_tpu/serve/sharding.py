"""Tensor-parallel sharding layer for the serving engine.

One replica of the serving engine stops meaning "one chip" here: an
:class:`EngineSharding` binds a model config to a 1-D ``tensor`` mesh
(2-D ``expert`` x ``tensor`` for Mixtral) over an ICI slice, resolves
the family's regex partition rules through the strict
``match_partition_rules`` gate (a matrix nobody wrote a rule for can
never silently replicate), and places both the weights and the paged
KV pool:

- Weights follow Megatron discipline (``models/llama.py``
  ``llama_sharding_rules``): column-parallel wq/wk/wv/w1/w3,
  row-parallel wo/w2, vocab-parallel embeddings; Mixtral adds
  expert-parallel w1/w3/w2 over the ``expert`` axis with a replicated
  router (``mixtral_sharding_rules``).
- The KV pool is HEAD-sharded: the head-major layout
  ``[n_kv_heads, n_pages, page_size, head_dim]`` shards axis 0 over
  ``tensor``, so every KV operation the engine performs —
  ``paged_append`` scatter, decode gather, spec-verify, prefix-cache
  page copy — indexes only the page/offset axes and stays
  device-local. No KV collectives exist; the only cross-device
  traffic is the two standard psums per layer (row-parallel wo / w2
  reductions) plus the exact vocab-parallel logit reduction.

Everything host-side is device-count-agnostic by construction: the
scheduler plans in tokens and slots (it cannot even import jax —
``serve/scheduler.py`` ALLOWED_IMPORTS), the prefix cache and block
allocator track page NUMBERS (one logical page = one shard-local tile
on every device), and the spec decoder proposes token ids. One
``StepPlan`` drives a 1-chip and an N-way engine identically, which is
what the tp=1 vs tp=4 token-parity tests enforce.

Composition with the replica pool is 2-D scale-out: shard within a
slice x replicate across slices. ``replica_device_groups`` partitions
the host's devices into per-replica groups; each pool replica builds
its own EngineSharding over its group and reports one ``load_report``
either way, so ``EnginePool`` and the autoscaler compose unchanged.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.mesh.device_mesh import create_mesh
from ray_tpu.mesh.sharding import (ShardingRules, match_partition_rules,
                                   infer_sharding)

# KV pool layout contract (models/kv_cache.py): axis 0 is n_kv_heads,
# the ONLY sharded axis — pages/offsets stay whole on every device.
KV_POOL_SPEC = P("tensor", None, None, None)
# Int8 pools carry per-(kv_head, page) fp32 scales [KH, n_pages, 1]:
# same head axis sharded, so each device holds exactly the scales for
# its own page shards and quantize/dequantize stays device-local — no
# new collectives enter the KV path.
KV_SCALE_SPEC = P("tensor", None, None)


class ShardingConfigError(ValueError):
    """Engine sharding that cannot work: a model dimension that does
    not divide over the requested mesh, a device count that does not
    cover it, or rules that leave a large tensor unmatched."""


def family_sharding_rules(cfg) -> ShardingRules:
    """Serving partition rules for a model config, by family.

    fsdp=False on purpose: a serving replica shards over ``tensor``
    (and ``expert`` for MoE) only — data parallelism is the replica
    POOL's job (one whole mesh per replica), not an in-mesh axis.
    """
    from ray_tpu.models.mixtral import (MixtralConfig,
                                        mixtral_sharding_rules)
    if isinstance(cfg, MixtralConfig):
        return mixtral_sharding_rules(fsdp=False)
    from ray_tpu.models.llama import llama_sharding_rules
    return llama_sharding_rules(fsdp=False)


def validate_tp(cfg, tp: int, ep: int = 1) -> None:
    """Family-dispatched divisibility check; ShardingConfigError on
    any dimension that does not divide the mesh."""
    from ray_tpu.models.mixtral import MixtralConfig, mixtral_tp_validate
    try:
        if isinstance(cfg, MixtralConfig):
            mixtral_tp_validate(cfg, tp, ep)
        else:
            from ray_tpu.models.llama import llama_tp_validate
            if ep != 1:
                raise ValueError(
                    f"expert parallelism ep={ep} needs an MoE config, "
                    f"got {type(cfg).__name__}")
            llama_tp_validate(cfg, tp)
    except ValueError as e:
        raise ShardingConfigError(str(e)) from None


class EngineSharding:
    """A serving replica's mesh + partition rules + placement helpers.

    Built once per replica via :meth:`build`; the engine uses it to
    place weights and the KV pool at startup and to pin shardings at
    every host->device boundary. ``tp=1, ep=1`` is legal and places
    everything on one device — the degenerate mesh the parity tests
    lean on.
    """

    def __init__(self, mesh: Mesh, rules: ShardingRules, *,
                 tp: int, ep: int = 1):
        self.mesh = mesh
        self.rules = rules
        self.tp = int(tp)
        self.ep = int(ep)
        self.kv_sharding = NamedSharding(mesh, KV_POOL_SPEC)
        self.kv_scale_sharding = NamedSharding(mesh, KV_SCALE_SPEC)
        self.replicated = NamedSharding(mesh, P())

    def _kv_sharding_for(self, t):
        # rank dispatch: rank-4 page pools vs rank-3 scale tensors
        # (int8 mode) — both head-sharded on axis 0
        return (self.kv_scale_sharding if getattr(t, "ndim", 4) == 3
                else self.kv_sharding)

    @classmethod
    def build(cls, cfg, *, tp: int = 1, ep: int = 1,
              devices: Optional[Sequence[jax.Device]] = None,
              rules: Optional[ShardingRules] = None) -> "EngineSharding":
        """Validate ``cfg`` against a ``tp`` x ``ep`` mesh and build it.

        ``devices`` defaults to the first ``tp*ep`` of
        ``jax.devices()``; passing an explicit subset is how pool
        replicas land on disjoint slices (``replica_device_groups``).
        """
        validate_tp(cfg, tp, ep)
        n_need = tp * ep
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if len(devices) < n_need:
            raise ShardingConfigError(
                f"tp={tp} x ep={ep} needs {n_need} devices, have "
                f"{len(devices)}")
        mesh = create_mesh({"tensor": tp, "expert": ep},
                           devices=devices[:n_need])
        if rules is None:
            rules = family_sharding_rules(cfg)
        return cls(mesh, rules, tp=tp, ep=ep)

    # -- placement ---------------------------------------------------

    def shard_params(self, params):
        """Device-put the weight pytree per the rules, through the
        strict unmatched-path gate: every >=2-D tensor must be covered
        by an explicit rule or this raises (ShardingConfigError) —
        a silently replicated weight matrix costs a full copy of
        itself in every device's HBM."""
        try:
            match_partition_rules(self.rules, params,
                                  on_unmatched="raise")
        except ValueError as e:
            raise ShardingConfigError(str(e)) from None
        shardings = infer_sharding(params, self.rules, self.mesh)
        return jax.device_put(params, shardings)

    def place_kv_pool(self, pages: List[Any]):
        """Head-shard the paged KV pool: each layer's (pages_k,
        pages_v) splits axis 0 (kv heads) over ``tensor``. Page
        indices and in-page offsets are global coordinates valid on
        every device, so the host-side allocator / prefix cache /
        page tables need no changes. Int8 layers are 4-tuples (pages
        + per-page scales); rank-3 scale tensors pin to KV_SCALE_SPEC
        next to their head-sharded pages."""
        return [tuple(jax.device_put(t, self._kv_sharding_for(t))
                      for t in layer) for layer in pages]

    def replicate(self, x):
        """Commit a host value to the mesh replicated — the placement
        for page tables, positions, token chunks, and RNG keys (small
        operands every device needs whole)."""
        return jax.device_put(x, self.replicated)

    def constrain_kv(self, pages):
        """Inside-jit sharding constraint pinning a KV pool pytree to
        the head-sharded layout. Uses the concrete NamedSharding, so
        it binds without a mesh context manager; applied to every
        jitted step's output pool it guarantees GSPMD can never
        reshard the pool (which would both break donation aliasing
        and introduce the KV collectives this layer exists to
        avoid). Rank-dispatches so int8 scale tensors pin to their
        own spec alongside the pages."""
        return jax.tree_util.tree_map(
            lambda t: jax.lax.with_sharding_constraint(
                t, self._kv_sharding_for(t)), pages)

    def describe(self) -> dict:
        return {"tp": self.tp, "ep": self.ep,
                "devices": int(self.tp * self.ep)}


def replica_device_groups(n_replicas: int, devices_per_replica: int,
                          devices: Optional[Sequence[jax.Device]] = None,
                          ) -> List[List[jax.Device]]:
    """Partition the host's devices into per-replica groups for 2-D
    scale-out (replicate across slices x shard within a slice).

    Groups are disjoint while devices last; once exhausted they wrap
    around (replica i reuses the group at ``i % n_full_groups``) —
    time-sharing devices is meaningless on real chips but exactly
    what a forced-multi-device CPU host mesh wants for pool tests.
    """
    if n_replicas <= 0 or devices_per_replica <= 0:
        raise ShardingConfigError(
            f"need n_replicas >= 1 and devices_per_replica >= 1, got "
            f"{n_replicas} x {devices_per_replica}")
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if len(devices) < devices_per_replica:
        raise ShardingConfigError(
            f"devices_per_replica={devices_per_replica} exceeds the "
            f"{len(devices)} visible devices")
    n_full = len(devices) // devices_per_replica
    groups = []
    for i in range(n_replicas):
        j = i if i < n_full else i % n_full
        lo = j * devices_per_replica
        groups.append(devices[lo:lo + devices_per_replica])
    return groups
